"""Batched CASPaxos as a single XLA program.

CASPaxos (reference ``caspaxos/``; per-actor analog
``protocols/caspaxos.py``): a replicated register WITHOUT a log. Leaders
cycle Idle -> Phase1 -> Phase2 -> Idle per request batch
(caspaxos/Leader.scala state ADT); acceptors keep (round, voteRound,
voteValue); a nack sends the leader into a randomized backoff before it
retries in a higher owned round (WaitingToRecover); phase 1 adopts the
value of the HIGHEST vote round and applies the change function to it.

TPU-first design: ``G`` independent registers are the replica axis, each
with ``L`` competing leaders (rounds owned round-robin: leader l owns
rounds r == l mod L, the ClassicRoundRobin of the reference) and
``2f+1`` acceptors. The reference's int-set register with set-union
change function becomes a 32-bit mask with OR — the same commutative
idempotent monoid, exactly representable on device: clients add single
bits, phase 2 proposes ``safe_value | pending_bits``, and the register's
whole history is auditable from the masks.

Message discipline learned from the other backends: every in-flight
message CARRIES its round and phase (captured at send), so stragglers
processed after a leader moved on are tagged stale and dropped rather
than misread against live state; within a tick an acceptor processes
only its highest-round arrival and nacks the rest (a deterministic
serialization of same-tick deliveries).

THE CASPaxos safety property — all chosen register values form a chain
under set inclusion — is checked on device at every commit
(``chain_violations``), including the same-tick multi-leader commit race
(the higher-round value must contain every lower-round one).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_ROUND,
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_delivered,
    bit_latency,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Leader status.
L_IDLE = 0
L_P1 = 1
L_P2 = 2
L_BACK = 3  # randomized backoff after a nack (WaitingToRecover)

NBITS = 32  # register width (bits = client ops)


@dataclasses.dataclass(frozen=True)
class BatchedCasPaxosConfig:
    """G registers x L leaders x (2f+1) acceptors."""

    f: int = 1
    num_registers: int = 4  # G
    num_leaders: int = 2  # L: competing proposers per register
    op_rate: float = 0.25  # P(a new client bit arrives per leader per tick)
    lat_min: int = 1
    lat_max: int = 3
    backoff_min: int = 2  # nack backoff (uniform, in ticks)
    backoff_max: int = 10
    # Unified in-graph fault injection (tpu/faults.py), TCP semantics:
    # CASPaxos leaders have no phase timeout, so drops become
    # retransmission penalties and an acceptor-axis partition BUFFERS
    # the dn/up exchanges until the heal tick (a never-healing cut of a
    # quorum permanently stalls affected leaders — that is the real
    # failure mode). FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): a shaping plan
    # replaces the Bernoulli op_rate draw with the engine's per-lane
    # admission (lane axis = the L x G leaders; an op is one register
    # bit, so each lane admits at most one op per tick and the FIFO
    # backlog carries the rest). WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def quorum(self) -> int:
        return self.f + 1

    def __post_init__(self):
        assert self.f >= 1
        assert self.num_leaders >= 1
        assert 0.0 <= self.op_rate <= 1.0
        assert 1 <= self.lat_min <= self.lat_max
        assert 1 <= self.backoff_min <= self.backoff_max
        self.faults.validate(axis=self.n)
        self.workload.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedCasPaxosState:
    """Shapes: [G] registers, [L, G] leaders, [A, G] acceptors,
    [A, L, G] messages, [G, NBITS] per-bit bookkeeping."""

    # Leaders.
    l_status: jnp.ndarray  # [L, G]
    l_round: jnp.ndarray  # [L, G] current round (owned: r % L == l)
    l_value: jnp.ndarray  # [L, G] value proposed in phase 2 (uint32 mask)
    l_pending: jnp.ndarray  # [L, G] client bits not yet chosen (uint32)
    l_seen_round: jnp.ndarray  # [L, G] max round seen in nacks
    backoff_until: jnp.ndarray  # [L, G]

    # Acceptors.
    a_round: jnp.ndarray  # [A, G] promised round
    a_vote_round: jnp.ndarray  # [A, G] (-1 = none)
    a_vote_value: jnp.ndarray  # [A, G] uint32 mask

    # Messages (payloads captured at send/processing time).
    dn_arrival: jnp.ndarray  # [A, L, G] leader -> acceptor
    dn_round: jnp.ndarray  # [A, L, G]
    dn_phase: jnp.ndarray  # [A, L, G] 1 | 2
    dn_value: jnp.ndarray  # [A, L, G] uint32 (phase 2)
    up_arrival: jnp.ndarray  # [A, L, G] acceptor -> leader
    up_round: jnp.ndarray  # [A, L, G] round the reply answers
    up_nack: jnp.ndarray  # [A, L, G] bool
    up_nack_round: jnp.ndarray  # [A, L, G] acceptor's round (fast-forward)
    up_vote_round: jnp.ndarray  # [A, L, G] phase-1b payload
    up_vote_value: jnp.ndarray  # [A, L, G] uint32

    # Register + per-bit bookkeeping.
    last_chosen: jnp.ndarray  # [G] uint32: newest chosen register value
    last_round: jnp.ndarray  # [G] round that chose last_chosen (-1)
    bit_issue: jnp.ndarray  # [G, NBITS] issue tick (INF = never issued)
    bit_done: jnp.ndarray  # [G, NBITS] bool: bit visible in a chosen value

    # Stats.
    commits: jnp.ndarray  # [] successful CAS round trips
    bits_issued: jnp.ndarray  # []
    bits_chosen: jnp.ndarray  # []
    nacks: jnp.ndarray  # []
    backoffs: jnp.ndarray  # []
    chain_violations: jnp.ndarray  # [] THE safety counter
    lat_sum: jnp.ndarray  # [] per-bit issue -> chosen latency
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedCasPaxosConfig) -> BatchedCasPaxosState:
    G, L, A = cfg.num_registers, cfg.num_leaders, cfg.n
    return BatchedCasPaxosState(
        l_status=jnp.zeros((L, G), DTYPE_STATUS),
        l_round=(
            jnp.arange(L, dtype=DTYPE_ROUND)[:, None]
            - DTYPE_ROUND(L) * jnp.ones((L, G), DTYPE_ROUND)
        ),
        # Distinct buffers (not one shared array): run_ticks donates the
        # state, and XLA rejects a donated buffer appearing twice.
        l_value=jnp.zeros((L, G), jnp.uint32),
        l_pending=jnp.zeros((L, G), jnp.uint32),
        l_seen_round=jnp.full((L, G), -1, DTYPE_ROUND),
        backoff_until=jnp.full((L, G), INF, jnp.int32),
        a_round=jnp.full((A, G), -1, DTYPE_ROUND),
        a_vote_round=jnp.full((A, G), -1, DTYPE_ROUND),
        a_vote_value=jnp.zeros((A, G), jnp.uint32),
        dn_arrival=jnp.full((A, L, G), INF, jnp.int32),
        dn_round=jnp.full((A, L, G), -1, DTYPE_ROUND),
        dn_phase=jnp.zeros((A, L, G), DTYPE_STATUS),
        dn_value=jnp.zeros((A, L, G), jnp.uint32),
        up_arrival=jnp.full((A, L, G), INF, jnp.int32),
        up_round=jnp.full((A, L, G), -1, DTYPE_ROUND),
        up_nack=jnp.zeros((A, L, G), bool),
        up_nack_round=jnp.full((A, L, G), -1, DTYPE_ROUND),
        up_vote_round=jnp.full((A, L, G), -1, DTYPE_ROUND),
        up_vote_value=jnp.zeros((A, L, G), jnp.uint32),
        last_chosen=jnp.zeros((G,), jnp.uint32),
        last_round=jnp.full((G,), -1, DTYPE_ROUND),
        bit_issue=jnp.full((G, NBITS), INF, jnp.int32),
        bit_done=jnp.zeros((G, NBITS), bool),
        commits=jnp.zeros((), jnp.int32),
        bits_issued=jnp.zeros((), jnp.int32),
        bits_chosen=jnp.zeros((), jnp.int32),
        nacks=jnp.zeros((), jnp.int32),
        backoffs=jnp.zeros((), jnp.int32),
        chain_violations=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_leaders * cfg.num_registers, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedCasPaxosConfig,
    state: BatchedCasPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedCasPaxosState:
    G, L, A = cfg.num_registers, cfg.num_leaders, cfg.n
    Q = cfg.quorum
    k3, k2 = jax.random.split(key)
    bits3 = jax.random.bits(k3, (A, L, G))  # [0:8) dn lat, [8:16) up lat
    bits2 = jax.random.bits(k2, (L, G))  # [0:8) backoff, [8:16) op draw,
    #                                      [16:21) new-bit index
    dn_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    up_lat = bit_latency(bits3, 8, cfg.lat_min, cfg.lat_max)
    backoff = bit_latency(bits2, 0, cfg.backoff_min, cfg.backoff_max)

    # Unified fault injection (tpu/faults.py), TCP semantics: drops are
    # retransmission penalties on the leg's latency; a partition of
    # acceptor rows buffers both legs until the heal tick. The dn/up
    # arrival offsets below replace every `t + *_lat` write; under a
    # none plan they ARE `t + *_lat` (structural no-op).
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    if fp.active:
        kf = faults_mod.fault_key(key)
        dn_lat = faults_mod.tcp_latency(fp, jax.random.fold_in(kf, 0),
                                        (A, L, G), dn_lat, rates=frates)
        up_lat = faults_mod.tcp_latency(fp, jax.random.fold_in(kf, 1),
                                        (A, L, G), up_lat, rates=frates)
    dn_arr = t + dn_lat
    up_arr = t + up_lat
    if fp.has_partition:
        cut = ~faults_mod.partition_row(fp, t, A)[:, None, None]
        dn_arr = faults_mod.defer_to_heal(fp, dn_arr, cut)
        up_arr = faults_mod.defer_to_heal(fp, up_arr, cut)

    # ---- 1. Acceptors process dn arrivals (CasAcceptor.receive). Within
    # a tick an acceptor takes only its HIGHEST-round arrival and nacks
    # the rest — a deterministic serialization of same-tick deliveries
    # (rounds are unique across leaders: r % L == l).
    arr = state.dn_arrival == t  # [A, L, G]
    best_round = jnp.max(jnp.where(arr, state.dn_round, -1), axis=1)  # [A, G]
    winner = arr & (state.dn_round == best_round[:, None, :])
    p1_win = winner & (state.dn_phase == 1)
    p2_win = winner & (state.dn_phase == 2)

    # Phase 1a: promise iff round > promised round, reply votes; else nack
    # (CasAcceptor: msg.round > self.round).
    p1_ok = p1_win & (state.dn_round > state.a_round[:, None, :])
    # Phase 2a: vote iff round >= promised round.
    p2_ok = p2_win & (state.dn_round >= state.a_round[:, None, :])
    ok = p1_ok | p2_ok
    new_round = jnp.max(
        jnp.where(ok, state.dn_round, -1), axis=1
    )  # [A, G] (at most one ok per acceptor: the winner)
    a_round = jnp.maximum(state.a_round, new_round)
    vote_now = jnp.any(p2_ok, axis=1)  # [A, G]
    voted_round = jnp.max(jnp.where(p2_ok, state.dn_round, -1), axis=1)
    voted_value = jnp.max(jnp.where(p2_ok, state.dn_value, 0), axis=1)
    a_vote_round = jnp.where(vote_now, voted_round, state.a_vote_round)
    a_vote_value = jnp.where(vote_now, voted_value, state.a_vote_value)

    # Replies: every arrival gets one (ack with payload, or nack). The
    # phase-1b vote payload is captured AFTER this tick's vote (an
    # acceptor that just voted reports that vote — same-tick accuracy).
    nack = arr & ~ok
    up_arrival = jnp.where(arr, up_arr, state.up_arrival)
    up_round = jnp.where(arr, state.dn_round, state.up_round)
    up_nack = jnp.where(arr, nack, state.up_nack)
    up_nack_round = jnp.where(arr, a_round[:, None, :], state.up_nack_round)
    up_vote_round = jnp.where(
        arr, a_vote_round[:, None, :], state.up_vote_round
    )
    up_vote_value = jnp.where(
        arr, a_vote_value[:, None, :], state.up_vote_value
    )
    dn_arrival = jnp.where(arr, INF, state.dn_arrival)

    # ---- 2. Leaders process up arrivals. Replies for a round other than
    # the leader's current round are stale — dropped (the reference
    # leader's `msg.round != round` guards).
    got = (up_arrival <= t) & (up_round == state.l_round[None, :, :])
    got_nack = got & up_nack
    got_ack = got & ~up_nack

    # Nacks: back off with a randomized timer, remember the round to
    # jump past (CasLeader._handle_nack -> WaitingToRecover).
    nacked = (
        ((state.l_status == L_P1) | (state.l_status == L_P2))
        & jnp.any(got_nack, axis=0)
    )
    l_seen_round = jnp.maximum(
        state.l_seen_round, jnp.max(jnp.where(got, up_nack_round, -1), axis=0)
    )
    nacks = state.nacks + jnp.sum(got_nack)
    backoffs = state.backoffs + jnp.sum(nacked)

    # Phase-1 completion: a quorum of acks; adopt the HIGHEST vote round's
    # value (classic CASPaxos safety; the module docstring of the
    # per-actor impl documents the deliberate divergence from the
    # reference's minBy), apply the change function (OR the pending
    # bits), move to phase 2.
    ack_count = jnp.sum(got_ack, axis=0)  # [L, G]
    p1_done = (state.l_status == L_P1) & ~nacked & (ack_count >= Q)
    best_vr = jnp.max(jnp.where(got_ack, up_vote_round, -1), axis=0)
    safe = jnp.max(
        jnp.where(
            got_ack & (up_vote_round == best_vr[None, :, :]),
            up_vote_value,
            0,
        ),
        axis=0,
    )  # [L, G] (all max-round votes carry the same value)
    new_value = safe | state.l_pending
    l_value = jnp.where(p1_done, new_value, state.l_value)

    # Phase-2 completion: a quorum of acks chooses the value.
    p2_done = (state.l_status == L_P2) & ~nacked & (ack_count >= Q)

    # ---- 3. Commit: update the register, check the chain property.
    # Commits arrive out of round order: a slow quorum can complete a
    # LOWER round after a higher one already advanced the register (its
    # value is then guaranteed contained — the higher round's phase-1
    # quorum intersected its votes). Track the register's round and only
    # advance on a strictly higher one; the chain checks are therefore
    # DIRECTIONAL: newer-than-register commits must contain the register,
    # and every commit must be contained in the newest value standing
    # after this tick.
    committed_mask = p2_done  # [L, G]
    commit_round = jnp.where(committed_mask, state.l_round, -1)
    max_cr = jnp.max(commit_round, axis=0)  # [G]
    advance = max_cr > state.last_round
    final_value = jnp.max(
        jnp.where(commit_round == max_cr[None, :], state.l_value, 0), axis=0
    )  # [G] value of the max-round commit this tick
    newest = jnp.where(advance, final_value, state.last_chosen)  # [G]
    newer = committed_mask & (commit_round > state.last_round[None, :])
    contains_prev = (
        state.l_value & state.last_chosen[None, :]
    ) == state.last_chosen[None, :]
    contained_in_newest = (
        state.l_value & newest[None, :]
    ) == state.l_value
    chain_violations = state.chain_violations + jnp.sum(
        (newer & ~contains_prev)
        | (committed_mask & ~contained_in_newest)
    )
    last_chosen = newest
    last_round = jnp.where(advance, max_cr, state.last_round)
    commits = state.commits + jnp.sum(committed_mask)

    # Per-bit latency: bits newly visible in the register.
    bit_mat = jnp.uint32(1) << jnp.arange(NBITS, dtype=jnp.uint32)  # [NBITS]
    now_set = (last_chosen[:, None] & bit_mat[None, :]) != 0  # [G, NBITS]
    newly_done = now_set & ~state.bit_done
    bit_done = state.bit_done | now_set
    blat = jnp.where(newly_done, t - state.bit_issue, 0)
    bits_chosen = state.bits_chosen + jnp.sum(newly_done)
    lat_sum = state.lat_sum + jnp.sum(blat)
    bbins = jnp.clip(blat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly_done.astype(jnp.int32).ravel(), bbins.ravel(), LAT_BINS
    )

    # Committed pending bits retire (idempotent union: anything of ours
    # now in the register needs no re-proposal).
    cleared_bits = state.l_pending & jnp.where(
        committed_mask, state.l_value, jnp.uint32(0)
    )
    l_pending = state.l_pending & ~cleared_bits

    # ---- 4. Leader transitions.
    l_status = state.l_status
    l_round = state.l_round
    backoff_until = state.backoff_until
    # Nack -> backoff.
    l_status = jnp.where(nacked, L_BACK, l_status)
    backoff_until = jnp.where(nacked, t + backoff, backoff_until)
    # P1 -> P2: send phase 2a to every acceptor.
    send_p2 = p1_done[None, :, :]
    dn_arrival = jnp.where(send_p2, dn_arr, dn_arrival)
    dn_round = jnp.where(send_p2, state.l_round[None, :, :], state.dn_round)
    dn_phase = jnp.where(send_p2, 2, state.dn_phase)
    dn_value = jnp.where(send_p2, l_value[None, :, :], state.dn_value)
    l_status = jnp.where(p1_done, L_P2, l_status)
    # P2 -> idle.
    l_status = jnp.where(p2_done, L_IDLE, l_status)
    # Clear replies of settled leaders (their round is over).
    settled = (nacked | p1_done | p2_done)[None, :, :]
    up_arrival = jnp.where(settled, INF, up_arrival)

    # ---- 5. New client ops: each leader receives a PRNG bit with
    # probability op_rate (CasClient.propose: a singleton int-set).
    # The shared never-quantize-nonzero-to-zero rule, via the shared
    # helper (bit_delivered returns True w.p. 1 - rate).
    if wl.active:
        # Workload admission (tpu/workload.py): the engine's per-lane
        # cap replaces the Bernoulli op_rate draw (>=1 queued/ready op
        # admits one bit this tick). A drawn bit already pending on the
        # lane is absorbed idempotently and NOT counted admitted, so
        # the closed-loop window stays conserved.
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, L * G)
        adm = workload_mod.admission(wl, wls, wl_writes).reshape(L, G)
        new_op = adm >= 1
    else:
        new_op = ~bit_delivered(bits2, 8, cfg.op_rate)
    new_bit_idx = ((bits2 >> 16) & jnp.uint32(0x1F)).astype(jnp.uint32)
    new_bit = jnp.where(new_op, jnp.uint32(1) << new_bit_idx, jnp.uint32(0))
    if wl.active:
        fresh_bit = new_bit & ~l_pending
        wls = workload_mod.finish(
            wl, wls, t, wl_writes,
            jax.lax.population_count(fresh_bit)
            .astype(jnp.int32).reshape(L * G),
            jax.lax.population_count(cleared_bits)
            .astype(jnp.int32).reshape(L * G),
        )
    l_pending = l_pending | new_bit
    # Per-bit issue bookkeeping (first issue wins).
    issued_now = jnp.zeros((G, NBITS), bool)
    for l in range(L):  # L is tiny and static
        m = (new_bit[l][:, None] & bit_mat[None, :]) != 0
        issued_now = issued_now | m
    first_issue = issued_now & (state.bit_issue == INF) & ~bit_done
    bit_issue = jnp.where(first_issue, t, state.bit_issue)
    bits_issued = state.bits_issued + jnp.sum(first_issue)

    # ---- 6. Start/retry phase 1: an idle leader with pending bits, or a
    # backoff that expired, picks its next owned round above everything
    # it has seen and sends phase 1a to every acceptor
    # (CasLeader._transition_to_phase1; ClassicRoundRobin ownership).
    ready = (
        ((l_status == L_IDLE) & (l_pending != 0))
        | ((l_status == L_BACK) & (t >= backoff_until))
    )
    l_iota = jnp.arange(L, dtype=l_round.dtype)[:, None]
    floor = jnp.maximum(l_round, l_seen_round)
    # Smallest r > floor with r % L == l.
    next_round = floor + ((l_iota - floor) % L)
    next_round = jnp.where(next_round <= floor, next_round + L, next_round)
    l_round = jnp.where(ready, next_round, l_round)
    send_p1 = ready[None, :, :]
    dn_arrival = jnp.where(send_p1, dn_arr, dn_arrival)
    dn_round = jnp.where(send_p1, l_round[None, :, :], dn_round)
    dn_phase = jnp.where(send_p1, 1, dn_phase)
    l_status = jnp.where(ready, L_P1, l_status)
    backoff_until = jnp.where(ready, INF, backoff_until)
    up_arrival = jnp.where(send_p1, INF, up_arrival)  # drop stale replies

    # Telemetry: newly issued register bits are "proposals", CAS round
    # trips "commits", bits first visible in a chosen value "executes";
    # nacked leaders re-entering phase 1 are the retry plane.
    tel = record(
        state.telemetry,
        proposals=bits_issued - state.bits_issued,
        phase1_msgs=A * jnp.sum(ready),
        phase2_msgs=A * jnp.sum(p1_done),
        commits=commits - state.commits,
        executes=bits_chosen - state.bits_chosen,
        retries=backoffs - state.backoffs,
        queue_depth=jnp.sum(
            (state.bit_issue < INF) & ~bit_done
        ),
        queue_capacity=G * NBITS,
        lat_hist_delta=lat_hist - state.lat_hist,
    )
    # Span sampler (telemetry.record_spans — the generic plumbing):
    # register-bit lifecycles. Mapping: group = register, "ring" axis =
    # the NBITS bit positions, slot id = the bit index (bits are
    # issue-once — ids never recycle, so slot_ids needs no head
    # arithmetic). "proposed" = a bit's first issue into a leader's
    # pending set; phase-1 mark = any leader finished phase 1 on the
    # register; "voted" = an acceptor's vote value carries the bit;
    # choice and execution are ONE event (a bit first visible in the
    # chosen register value — CASPaxos has no separate dispatch plane).
    # Structurally OFF at spans=0, like the counter ring.
    if telemetry_mod.span_slots(tel):
        bit_ids = jnp.broadcast_to(
            jnp.arange(NBITS, dtype=jnp.int32)[None, :], (G, NBITS)
        )
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=first_issue,
            slot_ids=bit_ids,
            phase1_mark=jnp.any(p1_done, axis=0),
            voted=jnp.any(
                vote_now[:, :, None]
                & ((a_vote_value[:, :, None] & bit_mat[None, None, :])
                   != 0),
                axis=0,
            ),
            newly_chosen=newly_done,
            retire_mask=newly_done,
        )

    return BatchedCasPaxosState(
        l_status=l_status,
        l_round=l_round,
        l_value=l_value,
        l_pending=l_pending,
        l_seen_round=l_seen_round,
        backoff_until=backoff_until,
        a_round=a_round,
        a_vote_round=a_vote_round,
        a_vote_value=a_vote_value,
        dn_arrival=dn_arrival,
        dn_round=dn_round,
        dn_phase=dn_phase,
        dn_value=dn_value,
        up_arrival=up_arrival,
        up_round=up_round,
        up_nack=up_nack,
        up_nack_round=up_nack_round,
        up_vote_round=up_vote_round,
        up_vote_value=up_vote_value,
        last_chosen=last_chosen,
        last_round=last_round,
        bit_issue=bit_issue,
        bit_done=bit_done,
        commits=commits,
        bits_issued=bits_issued,
        bits_chosen=bits_chosen,
        nacks=nacks,
        backoffs=backoffs,
        chain_violations=chain_violations,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedCasPaxosConfig,
    state: BatchedCasPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedCasPaxosState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedCasPaxosConfig, state: BatchedCasPaxosState, t
) -> dict:
    L = cfg.num_leaders
    # THE CASPaxos safety property: chosen values chain under inclusion.
    chain_ok = state.chain_violations == 0
    # Round ownership: leaders only use rounds r == l (mod L).
    l_iota = jnp.arange(L, dtype=jnp.int32)[:, None]
    owned_ok = jnp.all(state.l_round % L == (l_iota % L))
    # Acceptors never vote above their promise.
    promise_ok = jnp.all(state.a_vote_round <= state.a_round)
    # The register contains exactly the bits accounted as chosen.
    bit_mat = jnp.uint32(1) << jnp.arange(NBITS, dtype=jnp.uint32)
    reg_bits = (state.last_chosen[:, None] & bit_mat[None, :]) != 0
    books_ok = jnp.all(reg_bits <= state.bit_done) & (
        state.bits_chosen == jnp.sum(state.bit_done)
    )
    # A vote's value is always a superset of no chosen value? (Votes may
    # run ahead of commits; the enforceable direction is that the
    # REGISTER never loses bits, covered by chain_ok.) Statuses in range.
    status_ok = jnp.all((state.l_status >= L_IDLE) & (state.l_status <= L_BACK))
    return {
        "chain_ok": chain_ok,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "owned_ok": owned_ok,
        "promise_ok": promise_ok,
        "books_ok": books_ok,
        "status_ok": status_ok,
    }


def stats(cfg: BatchedCasPaxosConfig, state: BatchedCasPaxosState, t) -> dict:
    done = int(state.bits_chosen)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (done + 1) // 2)).argmax())
        if done
        else -1
    )
    return {
        "ticks": int(t),
        "commits": int(state.commits),
        "bits_issued": int(state.bits_issued),
        "bits_chosen": done,
        "nacks": int(state.nacks),
        "backoffs": int(state.backoffs),
        "bit_latency_p50_ticks": p50,
        "chain_violations": int(state.chain_violations),
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedCasPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedCasPaxosConfig(
        num_registers=4, num_leaders=2, op_rate=0.3, faults=faults,
        workload=workload,
    )
