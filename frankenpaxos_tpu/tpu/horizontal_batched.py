"""Batched Horizontal MultiPaxos as a single XLA program: configuration
reconfiguration AS A LOG VALUE with the s+alpha chunk-activation pipeline
(reference ``horizontal/Leader.scala:216-250`` Chunk, ``:459-498`` choose
-> ``activeFirstSlots += slot + alpha``, ``:920-960`` chunk split;
per-actor analog ``protocols/horizontal.py``).

The defining mechanism of the horizontal family: the log is divided into
CHUNKS, each owned by one acceptor configuration. To reconfigure, the
leader proposes a ``Configuration`` value into the log like any command;
when it is chosen at slot ``s`` and the chosen watermark executes past
it, a new chunk activates at ``firstSlot = s + alpha`` (the old chunk's
``lastSlot`` becomes ``s + alpha - 1``), and the new configuration runs
phase 1 before its chunk may choose anything. The ``alpha`` pipeline
bound (``Leader.scala:638-646``: never more than alpha slots past the
watermark) is what makes ``s + alpha`` safe: no old-chunk proposal can
exist at or beyond the new chunk's first slot.

TPU-first layout: ``G`` independent horizontal logs (groups) advance in
lockstep arrays. Each group owns an acceptor pool of ``2n`` rows
(``n = 2f+1``) — two BANKS that alternate as the active configuration
(epoch parity selects the bank), which models "reconfigure to a fresh
set of acceptors" with static shapes. One reconfiguration may be in
flight per group at a time (the reference supports a chunk list; the
periodic driver here never needs more than two live chunks).

Safety is checked device-side: every chosen slot holds an f+1 vote
quorum INSIDE the bank its chunk stamped on it and ZERO votes in the
other bank (bank isolation — the horizontal analog of "no value chosen
by the wrong configuration"), the alpha bound never overflows, and
chunk boundaries never interleave epochs.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_ROUND,
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_latency,
    ring_retire,
)
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Slot status.
EMPTY = 0
PROPOSED = 1
CHOSEN = 2

NO_VALUE = -1


@dataclasses.dataclass(frozen=True)
class BatchedHorizontalConfig:
    """Static (compile-time) simulation parameters."""

    f: int = 1
    num_groups: int = 8  # G: independent horizontal logs
    window: int = 32  # W: ring capacity (>= alpha)
    slots_per_tick: int = 2  # K: new proposals per group per tick
    alpha: int = 16  # pipeline bound: next_slot - watermark <= alpha
    lat_min: int = 1
    lat_max: int = 3
    retry_timeout: int = 16  # re-send Phase2a to the full bank after this
    # Propose a Configuration value into each group's log every this many
    # ticks (0 = never reconfigure). Groups are staggered by index so the
    # whole fleet doesn't reconfigure on the same tick.
    reconfigure_every: int = 0
    # Closed workload: stop proposing once each group allocated this many
    # slots (None = open).
    max_slots_per_group: Optional[int] = None
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + a POOL-axis partition (side bits over the 2n
    # rows — both banks) on the Phase2a/Phase2b/retry planes; UDP
    # semantics, the full-bank retries restore liveness after a heal.
    # Crash/revive stalls a group's leader (no proposals while down).
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes the per-group
    # admission over the K candidate slots (admission <= slots_per_tick
    # per tick; the FIFO backlog holds the rest). WorkloadPlan.none() =
    # saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Kernel-layer dispatch policy (ops/registry.py): the vote plane —
    # bank-masked acceptor votes, in-bank quorum count, choose, and the
    # bank-isolation ledger (tick steps 1-2) — routes through
    # ops.registry.dispatch as `horizontal_vote`.
    kernels: KernelPolicy = KernelPolicy()

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def pool(self) -> int:
        return 2 * self.n  # two banks

    @property
    def quorum(self) -> int:
        return self.f + 1

    def __post_init__(self):
        assert self.f >= 1
        assert self.window >= 2 * self.slots_per_tick
        assert 2 <= self.alpha <= self.window, (
            "the ring must hold the full alpha pipeline"
        )
        assert 1 <= self.lat_min <= self.lat_max
        if self.reconfigure_every:
            assert self.reconfigure_every >= 2
        self.faults.validate(axis=self.pool)
        self.workload.validate()
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedHorizontalState:
    """Shapes: [G] groups, [G, W] ring slots, [P, G, W] per-acceptor
    (P = 2n pool rows: bank 0 = rows [0, n), bank 1 = rows [n, 2n))."""

    next_slot: jnp.ndarray  # [G] next slot to allocate
    head: jnp.ndarray  # [G] chosen watermark (contiguous chosen prefix)

    status: jnp.ndarray  # [G, W] EMPTY | PROPOSED | CHOSEN
    is_config: jnp.ndarray  # [G, W] slot carries a Configuration value
    slot_epoch: jnp.ndarray  # [G, W] chunk epoch stamped at proposal (-1)
    propose_tick: jnp.ndarray  # [G, W] (INF = empty)
    last_send: jnp.ndarray  # [G, W] last Phase2a send tick
    p2a_arrival: jnp.ndarray  # [P, G, W] Phase2a arrival (INF)
    p2b_arrival: jnp.ndarray  # [P, G, W] Phase2b arrival at leader (INF)
    voted: jnp.ndarray  # [P, G, W] acceptor voted for the slot
    vote_epoch: jnp.ndarray  # [P, G, W] epoch the vote was cast under (-1)

    # Chunk machinery (one pending reconfiguration per group).
    # Leader liveness under a FaultPlan crash schedule (all-True and
    # untouched otherwise): a down leader proposes nothing.
    fault_alive: jnp.ndarray  # [G] bool

    epoch: jnp.ndarray  # [G] epoch of the OLDEST live chunk
    boundary: jnp.ndarray  # [G] firstSlot of the pending chunk (INF none)
    p1_done: jnp.ndarray  # [G] new bank finished phase 1
    p1a_arrival: jnp.ndarray  # [P, G] Phase1a arrival at new bank (INF)
    p1b_arrival: jnp.ndarray  # [P, G] Phase1b arrival at leader (INF)

    # Stats.
    committed: jnp.ndarray  # [] slots chosen (cumulative)
    executed: jnp.ndarray  # [] slots past the watermark (cumulative)
    reconfigs_proposed: jnp.ndarray  # [] Configuration values proposed
    reconfigs_done: jnp.ndarray  # [] chunks fully handed over
    alpha_stalls: jnp.ndarray  # [] proposal slots dropped by the alpha gate
    boundary_stalls: jnp.ndarray  # [] proposals stalled awaiting phase 1
    bank_violations: jnp.ndarray  # [] votes observed in the WRONG bank
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedHorizontalConfig) -> BatchedHorizontalState:
    G, W, P = cfg.num_groups, cfg.window, cfg.pool
    return BatchedHorizontalState(
        next_slot=jnp.zeros((G,), jnp.int32),
        head=jnp.zeros((G,), jnp.int32),
        status=jnp.zeros((G, W), DTYPE_STATUS),
        is_config=jnp.zeros((G, W), bool),
        slot_epoch=jnp.full((G, W), -1, DTYPE_ROUND),
        propose_tick=jnp.full((G, W), INF, jnp.int32),
        last_send=jnp.full((G, W), INF, jnp.int32),
        p2a_arrival=jnp.full((P, G, W), INF, jnp.int32),
        p2b_arrival=jnp.full((P, G, W), INF, jnp.int32),
        voted=jnp.zeros((P, G, W), bool),
        vote_epoch=jnp.full((P, G, W), -1, DTYPE_ROUND),
        fault_alive=jnp.ones((G,), bool),
        epoch=jnp.zeros((G,), DTYPE_ROUND),
        boundary=jnp.full((G,), INF, jnp.int32),
        p1_done=jnp.zeros((G,), bool),
        p1a_arrival=jnp.full((P, G), INF, jnp.int32),
        p1b_arrival=jnp.full((P, G), INF, jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        executed=jnp.zeros((), jnp.int32),
        reconfigs_proposed=jnp.zeros((), jnp.int32),
        reconfigs_done=jnp.zeros((), jnp.int32),
        alpha_stalls=jnp.zeros((), jnp.int32),
        boundary_stalls=jnp.zeros((), jnp.int32),
        bank_violations=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_groups, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def _bank_rows(cfg: BatchedHorizontalConfig) -> jnp.ndarray:
    """[P] bank index of each pool row (0 or 1)."""
    return (jnp.arange(cfg.pool, dtype=jnp.int32) >= cfg.n).astype(
        jnp.int32
    )


def tick(
    cfg: BatchedHorizontalConfig,
    state: BatchedHorizontalState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedHorizontalState:
    G, W, P, n = cfg.num_groups, cfg.window, cfg.pool, cfg.n
    w_iota = jnp.arange(W, dtype=jnp.int32)
    g_iota = jnp.arange(G, dtype=jnp.int32)
    bank_of_row = _bank_rows(cfg)  # [P]

    k_slot, k_p1 = jax.random.split(key)
    bits3 = jax.random.bits(k_slot, (P, G, W))  # [0:8) p2a lat,
    #                            [8:16) p2b lat, [16:24) retry lat
    bits1 = jax.random.bits(k_p1, (P, G))  # [0:8) p1a lat, [8:16) p1b lat
    p2a_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    p2b_lat = bit_latency(bits3, 8, cfg.lat_min, cfg.lat_max)
    retry_lat = bit_latency(bits3, 16, cfg.lat_min, cfg.lat_max)
    p1a_lat = bit_latency(bits1, 0, cfg.lat_min, cfg.lat_max)
    p1b_lat = bit_latency(bits1, 8, cfg.lat_min, cfg.lat_max)

    # Unified fault injection (tpu/faults.py): per-plane delivery masks
    # over the POOL axis; crash stalls a group's leader. none() skips
    # all of it at trace time.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    p2a_del = p2b_del = retry_del = None
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, P)[:, None, None]
        p2a_del, p2a_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (P, G, W), p2a_lat, link_up,
            rates=frates,
        )
        p2b_del, p2b_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (P, G, W), p2b_lat, link_up,
            rates=frates,
        )
        retry_del, retry_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 2), (P, G, W), retry_lat, link_up,
            rates=frates,
        )
    fault_alive = state.fault_alive
    if fp.has_crash:
        fault_alive = faults_mod.crash_step(
            fp, faults_mod.fault_key(key, 9), fault_alive, rates=frates
        )

    # ---- 1+2. The vote plane (one registry kernel, ops/horizontal.py):
    # acceptors of the slot's BANK process Phase2a arrivals (Acceptor.
    # scala votes only for chunks it belongs to; a Phase2a is only ever
    # SENT to the right bank, so the mask is defense in depth feeding
    # the bank_violations check), Phase2b replies schedule, the per-slot
    # in-bank quorum count chooses, and the bank-isolation ledger counts
    # wrong-bank votes. Scalar stats reduce the plane's masks out here.
    (
        status,
        p2a_arrival,
        p2b_arrival,
        voted,
        vote_epoch,
        newly_chosen,
        lat,
        viol,
    ) = ops_registry.dispatch(
        "horizontal_vote",
        cfg,
        state.slot_epoch,
        state.status,
        state.propose_tick,
        state.p2a_arrival,
        state.p2b_arrival,
        state.voted,
        state.vote_epoch,
        p2b_lat,
        p2b_del if p2b_del is not None else jnp.ones((P, G, W), bool),
        t,
        n=n,
        quorum=cfg.quorum,
    )
    committed = state.committed + jnp.sum(newly_chosen)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly_chosen.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    bank_violations = state.bank_violations + jnp.sum(viol)

    # ---- 3. Watermark advance (choose(), Leader.scala:459-498): walk
    # the contiguous CHOSEN prefix. A Configuration value crossing the
    # watermark schedules the next chunk at slot + alpha and launches
    # phase 1 against the new bank. One pending reconfiguration per
    # group: the proposal driver (step 5) never issues a second while
    # boundary is armed, so at most one config slot crosses per walk.
    pos_of_ord = jnp.mod(state.head[:, None] + w_iota[None, :], W)
    chosen_ord = jnp.take_along_axis(status == CHOSEN, pos_of_ord, axis=1)
    size = state.next_slot - state.head  # [G]
    in_ring_ord = w_iota[None, :] < size[:, None]  # ordinal-indexed
    # run [G] = slots the watermark advances; crossing [G, W] = the
    # position-indexed executed mask (shared ring-GC helper).
    run, crossing = ring_retire(chosen_ord & in_ring_ord, state.head)
    ordinal = jnp.mod(w_iota[None, :] - state.head[:, None], W)
    executed = state.executed + jnp.sum(run)
    # Config slot crossing: arm the boundary and start phase 1.
    config_cross = crossing & state.is_config
    cross_slot = jnp.max(
        jnp.where(config_cross, state.head[:, None] + ordinal, -1), axis=1
    )  # [G] (-1 = none; at most one by construction)
    arm = cross_slot >= 0
    boundary = jnp.where(arm, cross_slot + cfg.alpha, state.boundary)
    # Phase 1 to the NEW bank (epoch+1's rows).
    new_bank = jnp.mod(state.epoch + 1, 2)  # [G]
    in_new_bank = bank_of_row[:, None] == new_bank[None, :]  # [P, G]
    p1a_arrival = jnp.where(
        arm[None, :] & in_new_bank, t + p1a_lat, state.p1a_arrival
    )
    p1_done = jnp.where(arm, False, state.p1_done)

    head = state.head + run
    # Retire executed slots (free ring capacity).
    status = jnp.where(crossing, EMPTY, status)
    is_config = jnp.where(crossing, False, state.is_config)
    slot_epoch = jnp.where(crossing, -1, state.slot_epoch)
    propose_tick = jnp.where(crossing, INF, state.propose_tick)
    last_send = jnp.where(crossing, INF, state.last_send)
    clear3 = crossing[None, :, :]
    p2a_arrival = jnp.where(clear3, INF, p2a_arrival)
    p2b_arrival = jnp.where(clear3, INF, p2b_arrival)
    voted = jnp.where(clear3, False, voted)
    vote_epoch = jnp.where(clear3, -1, vote_epoch)

    # ---- 4. Phase 1 completes on f+1 Phase1bs from the new bank; the
    # old chunk hands over once the watermark reaches the boundary.
    p1a_now = state.p1a_arrival == t
    p1b_arrival = jnp.where(p1a_now, t + p1b_lat, state.p1b_arrival)
    p1a_arrival = jnp.where(p1a_now, INF, p1a_arrival)
    p1b_in = jnp.sum(
        (p1b_arrival <= t)
        & (bank_of_row[:, None] == jnp.mod(state.epoch + 1, 2)[None, :]),
        axis=0,
    )
    p1_done = p1_done | (
        (state.boundary < INF) & (p1b_in >= cfg.quorum)
    )
    # Handover needs BOTH: the watermark consumed the old chunk AND the
    # new bank finished phase 1 (the old chunk can drain fast when alpha
    # is small — the new chunk still may not choose before its phase 1).
    handover = (state.boundary < INF) & (head >= state.boundary) & p1_done
    epoch = jnp.where(handover, state.epoch + 1, state.epoch)
    boundary = jnp.where(handover, INF, boundary)
    reconfigs_done = state.reconfigs_done + jnp.sum(handover)
    p1b_arrival = jnp.where(handover[None, :], INF, p1b_arrival)

    # ---- 5. Propose (propose(), Leader.scala:617-660). Candidate slots
    # are the next K; each is gated by (a) the alpha pipeline bound, (b)
    # chunk ownership: slots below the boundary belong to the current
    # chunk (epoch), at/above it to the NEW chunk (epoch+1), which may
    # only propose once phase 1 is done. Periodically one slot carries a
    # Configuration value instead of a command (config-as-log-value).
    # Candidate gating runs in DELTA space (candidate j = slot
    # next_slot + j): proposals are contiguous in slot order, so a
    # blocked candidate blocks everything after it — and the ring wraps,
    # so a w-axis scan would visit candidates out of order.
    K = cfg.slots_per_tick
    k_iota = jnp.arange(K, dtype=jnp.int32)
    abs_k = state.next_slot[:, None] + k_iota[None, :]  # [G, K]
    want_k = jnp.ones((G, K), bool)
    # Workload admission (tpu/workload.py): the cap gates the K
    # candidate slots (per-tick admission is bounded by slots_per_tick;
    # the FIFO backlog carries the residual demand).
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, G)
        adm = workload_mod.admission(wl, wls, wl_writes)
        want_k = want_k & (k_iota[None, :] < adm[:, None])
    if fp.has_crash:
        # A crashed group leader proposes nothing until revival.
        want_k = want_k & fault_alive[:, None]
    if cfg.max_slots_per_group is not None:
        want_k = want_k & (abs_k < cfg.max_slots_per_group)
    alpha_ok_k = abs_k < (head + cfg.alpha)[:, None]
    past_boundary_k = abs_k >= boundary[:, None]
    chunk_ok_k = jnp.where(past_boundary_k, p1_done[:, None], True)
    ok_k = want_k & alpha_ok_k & chunk_ok_k
    count = jnp.sum(
        jnp.cumprod(ok_k.astype(jnp.int32), axis=1), axis=1
    )  # [G] contiguous admitted prefix
    alpha_stalls = state.alpha_stalls + jnp.sum(want_k & ~alpha_ok_k)
    boundary_stalls = state.boundary_stalls + jnp.sum(
        want_k & alpha_ok_k & ~chunk_ok_k
    )
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count,
            jnp.sum(newly_chosen, axis=1),
        )
    delta = jnp.mod(w_iota[None, :] - state.next_slot[:, None], W)
    abs_slot = state.next_slot[:, None] + delta  # [G, W]
    is_new = delta < count[:, None]
    new_epoch = jnp.where(
        abs_slot >= boundary[:, None], epoch[:, None] + 1, epoch[:, None]
    )  # [G, W]
    # Reconfiguration driver: group g proposes a Configuration value at
    # tick t iff reconfigure_every divides t + g's stagger, no boundary
    # is armed, no earlier Configuration is still in flight in the ring,
    # and the slot is a fresh FIRST candidate (delta == 0).
    if cfg.reconfigure_every:
        fire = (
            (jnp.mod(t + g_iota * 7, cfg.reconfigure_every) == 0)
            & (boundary == INF)
            & ~jnp.any(is_config, axis=1)
        )
        new_config = is_new & (delta == 0) & fire[:, None]
        reconfigs_proposed = state.reconfigs_proposed + jnp.sum(
            jnp.any(new_config, axis=1)
        )
    else:
        new_config = jnp.zeros((G, W), bool)
        reconfigs_proposed = state.reconfigs_proposed

    status = jnp.where(is_new, PROPOSED, status)
    is_config = jnp.where(is_new, new_config, is_config)
    slot_epoch = jnp.where(is_new, new_epoch, slot_epoch)
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    next_slot = state.next_slot + count
    # Send Phase2as to the slot's bank (full bank; thriftiness is the
    # flagship's dimension, not this family's).
    send_bank = jnp.mod(new_epoch, 2)
    send_rows = bank_of_row[:, None, None] == send_bank[None, :, :]
    send_p2a = is_new[None, :, :] & send_rows
    if p2a_del is not None:
        send_p2a = send_p2a & p2a_del
    p2a_arrival = jnp.where(send_p2a, t + p2a_lat, p2a_arrival)

    # ---- 6. Retries (resendPhase2as, Leader.scala:206-213).
    timed_out = (status == PROPOSED) & (t - last_send >= cfg.retry_timeout)
    resend_rows = (
        bank_of_row[:, None, None] == jnp.mod(slot_epoch, 2)[None, :, :]
    )
    resend = timed_out[None, :, :] & resend_rows
    if retry_del is not None:
        resend = resend & retry_del
    p2a_arrival = jnp.where(resend, t + retry_lat, p2a_arrival)
    last_send = jnp.where(timed_out, t, last_send)

    # Telemetry: phase-1 traffic is the new-bank handover exchange;
    # alpha/boundary stalls are backpressure drops (proposal slots the
    # gates refused this tick); leader_changes counts chunk handovers.
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase1_msgs=jnp.sum(arm[None, :] & in_new_bank)
        + jnp.sum(p1a_now),
        phase2_msgs=jnp.sum(send_p2a) + jnp.sum(resend),
        commits=committed - state.committed,
        executes=executed - state.executed,
        drops=(alpha_stalls - state.alpha_stalls)
        + (boundary_stalls - state.boundary_stalls),
        retries=jnp.sum(timed_out),
        leader_changes=reconfigs_done - state.reconfigs_done,
        queue_depth=next_slot.sum() - head.sum(),
        queue_capacity=G * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    return BatchedHorizontalState(
        next_slot=next_slot,
        head=head,
        status=status,
        is_config=is_config,
        slot_epoch=slot_epoch,
        propose_tick=propose_tick,
        last_send=last_send,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        voted=voted,
        vote_epoch=vote_epoch,
        fault_alive=fault_alive,
        epoch=epoch,
        boundary=boundary,
        p1_done=p1_done,
        p1a_arrival=p1a_arrival,
        p1b_arrival=p1b_arrival,
        committed=committed,
        executed=executed,
        reconfigs_proposed=reconfigs_proposed,
        reconfigs_done=reconfigs_done,
        alpha_stalls=alpha_stalls,
        boundary_stalls=boundary_stalls,
        bank_violations=bank_violations,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedHorizontalConfig,
    state: BatchedHorizontalState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedHorizontalState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedHorizontalConfig, state: BatchedHorizontalState, t
) -> dict:
    W = cfg.window
    w_iota = jnp.arange(W, dtype=jnp.int32)
    bank_of_row = _bank_rows(cfg)
    # THE horizontal safety property: every vote sits in the bank of the
    # epoch stamped on its slot (no cross-configuration quorums), and the
    # device-side ledger observed no violation.
    slot_bank = jnp.mod(state.slot_epoch, 2)
    row_matches = bank_of_row[:, None, None] == slot_bank[None, :, :]
    votes_in_place = jnp.all(~state.voted | row_matches)
    ledger_ok = state.bank_violations == 0
    # Vote epochs match their slot's stamp (a vote never outlives the
    # chunk that solicited it).
    vote_epoch_ok = jnp.all(
        ~state.voted | (state.vote_epoch == state.slot_epoch[None, :, :])
    )
    # Alpha pipeline bound (Leader.scala:638-646).
    alpha_ok = jnp.all(state.next_slot - state.head <= cfg.alpha)
    window_ok = jnp.all(
        (state.head <= state.next_slot)
        & (state.next_slot - state.head <= cfg.window)
    )
    # Chunk discipline: in-ring slots below an armed boundary carry the
    # current epoch; slots at/past it carry epoch+1.
    abs_slot = state.head[:, None] + jnp.mod(
        w_iota[None, :] - state.head[:, None], W
    )
    in_ring = (
        jnp.mod(w_iota[None, :] - state.head[:, None], W)
        < (state.next_slot - state.head)[:, None]
    )
    live = in_ring & (state.status != EMPTY)
    below = live & (abs_slot < state.boundary[:, None])
    above = live & (abs_slot >= state.boundary[:, None])
    chunk_ok = jnp.all(
        jnp.where(below, state.slot_epoch == state.epoch[:, None], True)
    ) & jnp.all(
        jnp.where(above, state.slot_epoch == state.epoch[:, None] + 1, True)
    )
    # Books.
    books_ok = (state.executed <= state.committed) & (
        state.reconfigs_done <= state.reconfigs_proposed
    )
    return {
        "votes_in_place": votes_in_place,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "ledger_ok": ledger_ok,
        "vote_epoch_ok": vote_epoch_ok,
        "alpha_ok": alpha_ok,
        "window_ok": window_ok,
        "chunk_ok": chunk_ok,
        "books_ok": books_ok,
    }


def stats(
    cfg: BatchedHorizontalConfig, state: BatchedHorizontalState, t
) -> dict:
    committed = int(state.committed)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (committed + 1) // 2)).argmax())
        if committed
        else -1
    )
    return {
        "ticks": int(t),
        "committed": committed,
        "executed": int(state.executed),
        "reconfigs_proposed": int(state.reconfigs_proposed),
        "reconfigs_done": int(state.reconfigs_done),
        "alpha_stalls": int(state.alpha_stalls),
        "boundary_stalls": int(state.boundary_stalls),
        "commit_latency_p50_ticks": p50,
        "commit_latency_mean_ticks": (
            float(state.lat_sum) / committed if committed else -1.0
        ),
        "bank_violations": int(state.bank_violations),
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedHorizontalConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedHorizontalConfig(
        num_groups=4, window=16, slots_per_tick=2, alpha=8,
        workload=workload,
        retry_timeout=8, faults=faults,
    )
