"""The five tracked benchmark configurations of BASELINE.json, as one
runner:

    python -m frankenpaxos_tpu.tpu.baseline_configs          # quick sizes
    python -m frankenpaxos_tpu.tpu.baseline_configs --full   # 10k/100k

  1. MultiPaxos f=1 smoke (batched backend, invariants).
  2. Compartmentalized grid-quorum MultiPaxos (2x3 flexible grid).
  3. EPaxos / Simple BPaxos 5-replica dependency graphs.
  4. Matchmaker reconfiguration churn: throughput and p50 latency with
     periodic acceptor-set reconfigurations vs a churn-free run.
  5. Flexible-quorum sweep, grid vs majority (100k acceptors with
     --full; the sweep shards over a device mesh when one is available).

Prints one JSON line per config. Runs on whatever backend jax selects;
force CPU with JAX_PLATFORMS=cpu (tests use tiny sizes).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def config1_multipaxos_smoke(full: bool) -> dict:
    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=64 if full else 8, window=32, slots_per_tick=4,
        lat_min=1, lat_max=3,
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(200)
    sim.block_until_ready()
    inv = sim.check_invariants()
    assert all(inv.values()), inv
    stats = sim.stats()
    return {
        "config": "multipaxos_f1_smoke",
        "committed": stats["committed"],
        "p50_latency_ticks": stats["commit_latency_p50_ticks"],
        "invariants_ok": True,
    }


def config2_grid(full: bool) -> dict:
    from frankenpaxos_tpu.tpu.grid_batched import (
        GridBatchedConfig,
        check_invariants,
        init_state,
        run_ticks,
    )
    import jax
    import jax.numpy as jnp

    cfg = GridBatchedConfig(rows=2, cols=3, window=256 if full else 64)
    state, t = run_ticks(
        cfg, init_state(cfg), jnp.int32(0), 300, jax.random.PRNGKey(0)
    )
    inv = {k: bool(v) for k, v in check_invariants(cfg, state, t).items()}
    assert all(inv.values()), inv
    return {
        "config": "grid_2x3_flexible",
        "committed": int(state.committed),
        "invariants_ok": True,
    }


def config3_depgraph(full: bool) -> dict:
    from frankenpaxos_tpu.tpu.epaxos_batched import (
        BatchedEPaxosConfig,
        check_invariants,
        init_state,
        run_ticks,
    )
    import jax
    import jax.numpy as jnp

    out = {}
    for name, bpaxos in [("epaxos", False), ("simplebpaxos", True)]:
        cfg = BatchedEPaxosConfig(
            num_columns=5,
            window=256 if full else 64,
            instances_per_tick=8 if full else 2,
            slow_path_rate=0.2,
            see_same_tick_rate=0.5,
            simplebpaxos=bpaxos,
        )
        ticks = 500 if full else 150
        t0 = time.perf_counter()
        state, t = run_ticks(
            cfg, init_state(cfg), jnp.int32(0), ticks, jax.random.PRNGKey(0)
        )
        jax.block_until_ready(state)
        dt = time.perf_counter() - t0
        inv = {k: bool(v) for k, v in check_invariants(cfg, state, t).items()}
        assert all(inv.values()), inv
        out[name] = {
            "executed": int(state.executed_total),
            "executed_per_sec": round(int(state.executed_total) / dt, 1),
            "mean_exec_latency_ticks": round(
                float(state.lat_sum) / max(1, int(state.executed_total)), 2
            ),
        }
    return {"config": "epaxos_bpaxos_5replica_depgraph", **out}


def config4_matchmaker_churn(full: bool) -> dict:
    """Matchmaker churn on the DEVICE-SIDE path: reconfigurations run
    inside the compiled scan (MatchA/MatchB quorum + phase-1 read quorum
    against the old config, multipaxos_batched tick step 0.5), not as
    host injections. A per-segment committed timeline exposes the
    dip/recovery signature (vldb20_matchmaker lt figure)."""
    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    def run(churn_every) -> dict:
        cfg = BatchedMultiPaxosConfig(
            f=1, num_groups=256 if full else 16, window=64, slots_per_tick=4,
            lat_min=1, lat_max=3, retry_timeout=16,
            reconfigure_every=churn_every or 0,
        )
        sim = TpuSimTransport(cfg, seed=3)
        sim.run(100)  # warm the pipeline
        sim.block_until_ready()
        base = sim.committed()
        timeline = []
        segments, seg_ticks = 20, 25
        for _ in range(segments):
            before = sim.committed()
            sim.run(seg_ticks)
            timeline.append(sim.committed() - before)
        sim.block_until_ready()
        inv = sim.check_invariants()
        assert all(inv.values()), inv
        stats = sim.stats()
        out = {
            "committed": sim.committed() - base,
            "per_tick": round((sim.committed() - base) / (segments * seg_ticks), 1),
            "p50_latency_ticks": stats["commit_latency_p50_ticks"],
            "reconfigurations": stats.get("reconfigurations", 0),
            "old_configs_gcd": stats.get("old_configs_gcd", 0),
            "timeline_committed_per_segment": timeline,
        }
        return out

    churn_free = run(None)
    churned = run(100)  # a reconfiguration wave every 100 ticks
    return {
        "config": "matchmaker_reconfiguration_churn",
        "churn_free": churn_free,
        "with_churn": churned,
        "throughput_retained": round(
            churned["per_tick"] / max(1e-9, churn_free["per_tick"]), 3
        ),
    }


def config5_flexible_sweep(full: bool) -> dict:
    from frankenpaxos_tpu.tpu.grid_batched import GridBatchedConfig, sweep

    if full:
        # 100k acceptors, grid vs flat-majority quorums.
        shapes = [(100, 1000), (10, 10000)]
        window = 64
    else:
        shapes = [(2, 3), (4, 8)]
        window = 32
    # Lossless + lossy points: exact thrifty quorums have zero loss
    # margin, so drops expose the modes' different retry economics
    # (grid: R re-sends wasted per lost transversal member; majority:
    # N/2+1 — the message-cost/robustness trade-off the sweep measures).
    configs = [
        GridBatchedConfig(
            rows=r, cols=c, mode=mode, window=window, drop_rate=d
        )
        for (r, c) in shapes
        for mode in ("grid", "majority")
        for d in ((0.0, 0.05) if not full else (0.0, 0.02))
    ]
    results = sweep(configs, num_ticks=200)
    return {"config": "flexible_quorum_sweep", "points": results}


CONFIGS = {
    "1": config1_multipaxos_smoke,
    "2": config2_grid,
    "3": config3_depgraph,
    "4": config4_matchmaker_churn,
    "5": config5_flexible_sweep,
}


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="frankenpaxos_tpu.tpu.baseline_configs"
    )
    parser.add_argument("--full", action="store_true",
                        help="production sizes (10k/100k acceptors)")
    parser.add_argument("configs", nargs="*", choices=list(CONFIGS),
                        help="subset to run (default: all)")
    args = parser.parse_args()
    for name in args.configs or list(CONFIGS):
        result = CONFIGS[name](args.full)
        print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
