"""Batched Scalog as a single XLA program.

Scalog (``scalog/``) decouples ordering from replication: shard servers
append client records to LOCAL logs and report their lengths; the
aggregator assembles the per-shard length vector into a CUT; a Paxos
layer orders cuts; and every replica projects committed cuts onto one
global log. The projection is pure index arithmetic — exactly the
"Scalog cuts -> cut prefix-sums" row of SURVEY §2.7:

  * A committed cut ``c`` is a vector of per-shard log lengths
    (monotone in every coordinate).
  * The records of shard ``s`` between consecutive cuts ``c'`` and
    ``c`` occupy global indices starting at
    ``sum(c') + prefix_sum(c - c')[s]`` — one cumulative sum across the
    shard axis per cut.

The batched model advances all of it elementwise: shard log lengths
grow stochastically per tick, the aggregator snapshots a cut every
``cut_every`` ticks, cuts commit after a sampled Paxos round trip, and
the global log length is the sum of the newest committed cut. Record
latency (append -> globally ordered) is tracked per shard via the
cut-lag histogram.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import INF, LAT_BINS, bit_latency
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState


@dataclasses.dataclass(frozen=True)
class BatchedScalogConfig:
    """Static parameters: S shards, a cut pipeline of depth P."""

    num_shards: int = 4
    max_inflight_cuts: int = 8  # P: cut-ordering pipeline depth
    cut_every: int = 2  # aggregator snapshot period (ticks)
    appends_per_tick: int = 4  # mean records appended per shard per tick
    append_jitter: int = 3  # uniform jitter on appends (load skew)
    lat_min: int = 1  # one-way latency in ticks
    lat_max: int = 3
    max_records_per_shard: Optional[int] = None
    # Unified in-graph fault injection (tpu/faults.py), TCP semantics:
    # drops/jitter delay the cut-ordering Paxos round; a SHARD-axis
    # partition stops the aggregator from assembling full cuts (cut
    # issue pauses — the global log stalls behind the cut side) until
    # the heal tick; crash/revive flaps the aggregator itself.
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): a shaping plan
    # replaces the stochastic append draw with the engine's per-shard
    # arrivals (shards absorb appends locally, so open-loop admission
    # is immediate); completions are records entering the global log.
    # WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Kernel-layer dispatch policy (ops/registry.py): the cut-commit
    # plane — the in-order commit scan, newest-cut projection, and
    # per-cut latency attribution (tick step 2) — routes through
    # ops.registry.dispatch as `scalog_cut_commit`.
    kernels: KernelPolicy = KernelPolicy()

    def __post_init__(self):
        assert self.num_shards >= 2
        assert self.max_inflight_cuts >= 2
        assert self.cut_every >= 1
        assert self.appends_per_tick >= 1
        assert 0 <= self.append_jitter <= self.appends_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        self.faults.validate(axis=self.num_shards)
        self.workload.validate()
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedScalogState:
    """Shapes: [S] shards, [P, S] in-flight cut ring."""

    local_len: jnp.ndarray  # [S] records appended to each shard's log

    cut_vec: jnp.ndarray  # [P, S] in-flight/committed cut vectors
    cut_commit_tick: jnp.ndarray  # [P] when the cut commits (INF = empty)
    cut_snap_tick: jnp.ndarray  # [P] when the cut was snapshotted
    cut_prev_snap: jnp.ndarray  # [P] the PREVIOUS cut's snapshot tick
    last_snap_tick: jnp.ndarray  # [] newest snapshot tick issued
    # Aggregator liveness under a FaultPlan crash schedule (True and
    # untouched otherwise); a down aggregator issues no cuts.
    agg_alive: jnp.ndarray  # [] bool
    next_cut: jnp.ndarray  # [] cuts issued so far
    committed_cuts: jnp.ndarray  # [] cuts committed so far

    global_len: jnp.ndarray  # [] committed global log length (sum of cut)
    last_committed_cut: jnp.ndarray  # [S] the newest committed cut vector
    lat_sum: jnp.ndarray  # [] sum of record ordering latencies (ticks)
    lat_count: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedScalogConfig) -> BatchedScalogState:
    S, P = cfg.num_shards, cfg.max_inflight_cuts
    return BatchedScalogState(
        local_len=jnp.zeros((S,), jnp.int32),
        cut_vec=jnp.zeros((P, S), jnp.int32),
        cut_commit_tick=jnp.full((P,), INF, jnp.int32),
        cut_snap_tick=jnp.full((P,), INF, jnp.int32),
        cut_prev_snap=jnp.zeros((P,), jnp.int32),
        last_snap_tick=jnp.zeros((), jnp.int32),
        agg_alive=jnp.ones((), bool),
        next_cut=jnp.zeros((), jnp.int32),
        committed_cuts=jnp.zeros((), jnp.int32),
        global_len=jnp.zeros((), jnp.int32),
        last_committed_cut=jnp.zeros((S,), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_count=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_shards, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def global_indices_of_cut(
    prev_cut: jnp.ndarray, cut: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The cut projection (Server.scala's cut -> global-log doc): for
    each shard, the [start, end) global index range of its records
    between ``prev_cut`` and ``cut`` — base ``sum(prev_cut)`` plus the
    EXCLUSIVE prefix sum of the per-shard deltas."""
    delta = cut - prev_cut
    base = jnp.sum(prev_cut)
    starts = base + jnp.cumsum(delta) - delta  # exclusive prefix sum
    return starts, starts + delta


def tick(
    cfg: BatchedScalogConfig,
    state: BatchedScalogState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedScalogState:
    """One tick: shards append, the aggregator snapshots a cut on its
    period, in-flight cuts commit after their Paxos round trip, and the
    global log extends to the newest committed cut."""
    S, P = cfg.num_shards, cfg.max_inflight_cuts
    bits = jax.random.bits(key, (S,))
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(cfg.faults, wls)

    # ---- 1. Shards append records (stochastic load skew). Under a
    # workload plan the engine's per-shard arrivals replace the native
    # draw (tpu/workload.py); shards absorb appends locally, so the
    # open-loop cap admits everything queued.
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, S)
        appends = workload_mod.admission(wl, wls, wl_writes)
    else:
        appends = cfg.appends_per_tick - cfg.append_jitter + bit_latency(
            bits, 0, 0, 2 * cfg.append_jitter
        ) if cfg.append_jitter else jnp.full(
            (S,), cfg.appends_per_tick, jnp.int32
        )
    if cfg.max_records_per_shard is not None:
        appends = jnp.minimum(
            appends,
            jnp.maximum(cfg.max_records_per_shard - state.local_len, 0),
        )
    local_len = state.local_len + appends

    # ---- 2. Cuts commit: any in-flight cut whose Paxos decision has
    # landed. Commit ORDER is cut-issue order (the Paxos log of cuts),
    # so a cut only commits once all earlier cuts have; model: a cut's
    # effective commit tick is the max over itself and predecessors
    # (cumulative max over the ring in issue order). One registry plane
    # (ops/scalog.py): the in-order commit scan, the newest-cut
    # projection, the PER-CUT record/latency attribution (each
    # committing cut's records waited from its own snapshot —
    # attributing everything to the newest cut would hide exactly the
    # head-of-line blocking the cumulative max models), and the
    # ring-slot frees; the scalar stats reduce the plane's outputs here.
    (
        new_cut,
        committed_now_asc,
        recs_asc,
        lag_asc,
        slot_committed,
        cut_commit_tick,
        cut_snap_tick,
    ) = ops_registry.dispatch(
        "scalog_cut_commit",
        cfg,
        state.cut_vec,
        state.cut_commit_tick,
        state.cut_snap_tick,
        state.cut_prev_snap,
        state.last_committed_cut,
        state.committed_cuts,
        state.next_cut,
        t,
    )
    n_new_commits = jnp.sum(committed_now_asc.astype(jnp.int32))
    committed_cuts = state.committed_cuts + n_new_commits
    global_len = jnp.sum(new_cut)
    lat_sum = state.lat_sum + jnp.sum(lag_asc * recs_asc)
    lat_count = state.lat_count + jnp.sum(recs_asc)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        recs_asc, jnp.clip(lag_asc, 0, LAT_BINS - 1), LAT_BINS
    )
    if wl.active:
        # Completions: records entering the GLOBAL log this tick
        # (new_cut is the per-shard committed prefix vector).
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, appends,
            new_cut - state.last_committed_cut,
        )

    # ---- 3. Aggregator snapshots a new cut on its period, if the
    # pipeline has room (ShardInfo -> proposed cut -> Paxos; commit after
    # 2 one-way hops to the ordering layer and back plus jitter). Shard
    # length reports piggyback every tick, so the snapshot sees the
    # current local lengths.
    room = (state.next_cut - committed_cuts) < P
    due = (t % cfg.cut_every) == 0
    issue = room & due
    # Unified fault injection (tpu/faults.py): a partitioned shard set
    # starves the aggregator of full length reports (no cut while the
    # cut is live); a crashed aggregator issues nothing until revival;
    # drops/jitter stretch the ordering round. none() skips all of it.
    fp = cfg.faults
    agg_alive = state.agg_alive
    if fp.has_partition:
        issue = issue & ~faults_mod.partition_active(fp, t)
    if fp.has_crash:
        agg_alive = faults_mod.crash_step(
            fp, faults_mod.fault_key(key, 9), agg_alive, rates=frates
        )
        issue = issue & agg_alive
    slot = state.next_cut % P
    paxos_lat = bit_latency(jax.random.bits(jax.random.fold_in(key, 1), ()), 0,
                            2 * cfg.lat_min, 2 * cfg.lat_max + 2)
    if fp.traced or fp.drop_rate > 0.0 or fp.jitter > 0:
        paxos_lat = faults_mod.tcp_latency(
            fp, faults_mod.fault_key(key, 1), (), paxos_lat, rates=frates
        )
    cut_vec = jnp.where(
        issue,
        state.cut_vec.at[slot].set(local_len),
        state.cut_vec,
    )
    cut_commit_tick = jnp.where(
        issue, cut_commit_tick.at[slot].set(t + paxos_lat), cut_commit_tick
    )
    cut_snap_tick = jnp.where(
        issue, cut_snap_tick.at[slot].set(t), cut_snap_tick
    )
    cut_prev_snap = jnp.where(
        issue,
        state.cut_prev_snap.at[slot].set(state.last_snap_tick),
        state.cut_prev_snap,
    )
    last_snap_tick = jnp.where(issue, t, state.last_snap_tick)
    next_cut = state.next_cut + jnp.where(issue, 1, 0)

    # Telemetry: cut issues are the "proposals", committed cuts the
    # "commits", newly ordered records the "executes"; phase2 traffic is
    # the Paxos round per issued cut; the queue gauge is the uncommitted
    # append backlog relative to the committed log.
    tel = record(
        state.telemetry,
        proposals=next_cut - state.next_cut,
        phase2_msgs=jnp.where(issue, 2, 0),
        commits=n_new_commits,
        executes=lat_count - state.lat_count,
        queue_depth=state.next_cut - committed_cuts,
        queue_capacity=P,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    # Span sampler (telemetry.record_spans — the generic plumbing):
    # CUT lifecycles through the ordering layer. Mapping: one pseudo-
    # group (the aggregator), ring pos = the in-flight cut ring slot,
    # slot id = the monotone CUT NUMBER (cut c lives at ring pos c % P
    # for its whole life; computed from the PRE-TICK committed floor so
    # a cut committing this tick still matches). Stages: proposed =
    # the aggregator snapshots the cut (step 3's issue), phase2_voted =
    # committed = executed = the Paxos decision lands and the global
    # log extends (step 2's in-order commit scan — one tick, by
    # construction), no phase-1 round on the cut log, retire same tick
    # (record_spans stamps completion before rolling the ring slot, so
    # commit + retire in one tick is the normal path). The commit is
    # >= 2*lat_min ticks after the snapshot, so proposed < committed
    # always. Structurally OFF at spans=0 (the serve loop sizes the
    # reservoir), like every other backend.
    if telemetry_mod.span_slots(tel):
        ring = jnp.arange(P, dtype=state.next_cut.dtype)
        commit_mask = slot_committed[None, :]
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=((ring == slot) & issue)[None, :],
            slot_ids=(
                state.committed_cuts
                + ((ring - state.committed_cuts) % P)
            )[None, :],
            new_slot_ids=jnp.full((1, P), state.next_cut),
            phase1_mark=jnp.zeros((1,), bool),
            voted=commit_mask,
            newly_chosen=commit_mask,
            retire_mask=commit_mask,
        )

    return BatchedScalogState(
        local_len=local_len,
        cut_vec=cut_vec,
        cut_commit_tick=cut_commit_tick,
        cut_snap_tick=cut_snap_tick,
        cut_prev_snap=cut_prev_snap,
        last_snap_tick=last_snap_tick,
        agg_alive=agg_alive,
        next_cut=next_cut,
        committed_cuts=committed_cuts,
        global_len=global_len,
        last_committed_cut=new_cut,
        lat_sum=lat_sum,
        lat_count=lat_count,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedScalogConfig,
    state: BatchedScalogState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedScalogState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedScalogConfig, state: BatchedScalogState, t
) -> dict:
    """Device-side safety checks; all booleans must be True."""
    # The committed cut never exceeds what shards actually appended, and
    # the global log is exactly its sum.
    cut_le_local = jnp.all(state.last_committed_cut <= state.local_len)
    global_is_sum = state.global_len == jnp.sum(state.last_committed_cut)
    # Cut pipeline bookkeeping.
    pipeline_ok = (
        (state.committed_cuts <= state.next_cut)
        & (state.next_cut - state.committed_cuts <= cfg.max_inflight_cuts)
    )
    # Cut monotonicity: every live in-flight cut was snapshotted after
    # the newest committed one, so its vector dominates it coordinate-
    # wise; a newest-slot indexing regression would break this.
    P = cfg.max_inflight_cuts
    ids = state.committed_cuts + jnp.arange(P, dtype=jnp.int32)
    live = ids < state.next_cut
    monotone = jnp.all(
        jnp.where(
            live[:, None],
            state.cut_vec[ids % P] >= state.last_committed_cut[None, :],
            True,
        )
    )
    return {
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "cut_le_local": cut_le_local,
        "global_is_sum": global_is_sum,
        "pipeline_ok": pipeline_ok,
        "monotone": monotone,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedScalogConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedScalogConfig(
        num_shards=4, faults=faults, workload=workload,
    )
