"""Batched flexible-quorum MultiPaxos: one grid (or majority) quorum
system over ALL acceptors, as a single XLA program.

The BASELINE "100k-acceptor flexible-quorum sweep (grid vs majority)"
configuration: instead of round-robin acceptor groups (see
``multipaxos_batched``), the whole cluster is ONE quorum system over
N = rows x cols acceptors (the flexible mode of ``multipaxos/Config.scala``
:19-25, quorums/Grid.scala):

  * grid mode: a phase-2 write quorum is one acceptor per row (a random
    "column transversal", Grid.randomWriteQuorum); a slot is chosen when
    EVERY row has at least one vote in — computed as a per-row any-vote
    reduction followed by an all-rows reduction;
  * majority mode: a write quorum is any ceil((N+1)/2) acceptors — a flat
    sum reduction (SimpleMajority).

State is [W, R, C]: W in-flight slots over the R x C acceptor grid.
Messages are PRNG-stamped arrival ticks exactly as in the grouped
backend; retries re-send to the full grid. The acceptor axes shard over a
device mesh by rows: a write quorum touches every row, so each tick's
quorum check is a tiny cross-device reduction over ICI (the grouped
backend's zero-communication property does not hold for grids —
that IS the flexible-quorum trade-off being measured).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    sample_delivered,
    sample_latency,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.multipaxos_batched import CHOSEN, EMPTY, PROPOSED
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record


def _delivered(cfg, key, shape):
    return sample_delivered(cfg.drop_rate, key, shape)


def _lat(cfg, key, shape):
    return sample_latency(cfg.lat_min, cfg.lat_max, key, shape)


@dataclasses.dataclass(frozen=True)
class GridBatchedConfig:
    rows: int = 4
    cols: int = 4
    mode: str = "grid"  # "grid" | "majority"
    window: int = 32
    slots_per_tick: int = 4
    lat_min: int = 1
    lat_max: int = 3
    drop_rate: float = 0.0
    retry_timeout: int = 16
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + a partition over the flattened acceptor grid
    # (row-major side bits) on the Phase2a/Phase2b/retry planes; UDP
    # semantics — the full-grid retries restore liveness after a heal.
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): the grid runs ONE
    # log, so the lane axis is a single lane shaping its per-tick
    # proposal admission. WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def num_acceptors(self) -> int:
        return self.rows * self.cols

    @property
    def majority_size(self) -> int:
        return self.num_acceptors // 2 + 1

    def __post_init__(self):
        assert self.mode in ("grid", "majority")
        assert self.rows >= 1 and self.cols >= 1
        assert self.window >= 2 * self.slots_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.drop_rate < 1.0
        self.faults.validate(axis=self.num_acceptors)
        self.workload.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GridBatchedState:
    next_slot: jnp.ndarray  # [] next slot sequence number
    head: jnp.ndarray  # [] lowest non-retired slot
    status: jnp.ndarray  # [W]
    propose_tick: jnp.ndarray  # [W]
    last_send: jnp.ndarray  # [W]
    chosen_tick: jnp.ndarray  # [W]
    replica_arrival: jnp.ndarray  # [W]
    p2a_arrival: jnp.ndarray  # [W, R, C]
    p2b_arrival: jnp.ndarray  # [W, R, C]
    committed: jnp.ndarray  # []
    retired: jnp.ndarray  # []
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    # Phase2a messages sent (thrifty first sends + full-grid retries).
    # THE quorum-system trade-off: a grid write quorum costs R messages,
    # a majority costs N/2+1 — but an exact thrifty quorum has zero loss
    # margin, so under drops the modes also diverge in retry traffic and
    # commit latency. int32: fine below ~2G sends per run.
    msgs_sent: jnp.ndarray  # []
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: GridBatchedConfig) -> GridBatchedState:
    W, R, C = cfg.window, cfg.rows, cfg.cols
    return GridBatchedState(
        next_slot=jnp.zeros((), jnp.int32),
        head=jnp.zeros((), jnp.int32),
        status=jnp.zeros((W,), DTYPE_STATUS),
        propose_tick=jnp.full((W,), INF, jnp.int32),
        last_send=jnp.full((W,), INF, jnp.int32),
        chosen_tick=jnp.full((W,), INF, jnp.int32),
        replica_arrival=jnp.full((W,), INF, jnp.int32),
        p2a_arrival=jnp.full((W, R, C), INF, jnp.int32),
        p2b_arrival=jnp.full((W, R, C), INF, jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        retired=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        msgs_sent=jnp.zeros((), jnp.int32),
        workload=workload_mod.make_state(cfg.workload, 1, cfg.faults),
        telemetry=make_telemetry(),
    )


def tick(cfg: GridBatchedConfig, state: GridBatchedState, t, key):
    W, R, C = cfg.window, cfg.rows, cfg.cols
    k_col, k_lat1, k_lat2, k_lat3, k_drop1, k_drop2, k_retry = (
        jax.random.split(key, 7)
    )
    w_iota = jnp.arange(W, dtype=jnp.int32)
    status = state.status

    # Per-plane delivery masks and latencies (same keys and draw order
    # as before), with the unified fault plan (tpu/faults.py) folded in:
    # partition side bits cover the flattened R*C acceptor grid.
    p2b_del = _delivered(cfg, k_drop1, (W, R, C))
    p2b_lat = _lat(cfg, k_lat1, (W, R, C))
    p2a_del = _delivered(cfg, k_drop2, (W, R, C))
    p2a_lat = _lat(cfg, k_lat2, (W, R, C))
    retry_lat = _lat(cfg, k_retry, (W, R, C))
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    retry_del = None
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, R * C).reshape(1, R, C)
        f_del, p2b_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (W, R, C), p2b_lat, link_up,
            rates=frates,
        )
        p2b_del = p2b_del & f_del
        f_del, p2a_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (W, R, C), p2a_lat, link_up,
            rates=frates,
        )
        p2a_del = p2a_del & f_del
        retry_del, retry_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 2), (W, R, C), retry_lat, link_up,
            rates=frates,
        )

    # 1. Acceptors vote on Phase2a arrivals.
    arrived = state.p2a_arrival == t
    p2b_arrival = jnp.where(
        arrived & p2b_del,
        jnp.minimum(state.p2b_arrival, t + p2b_lat),
        state.p2b_arrival,
    )

    # 2. Quorum check.
    votes_in = p2b_arrival <= t  # [W, R, C]
    if cfg.mode == "grid":
        # Write quorum = every ROW has a vote in (Grid.isWriteQuorum).
        row_has_vote = jnp.any(votes_in, axis=2)  # [W, R]
        quorum = jnp.all(row_has_vote, axis=1)  # [W]
    else:
        quorum = jnp.sum(votes_in, axis=(1, 2)) >= cfg.majority_size
    newly_chosen = (status == PROPOSED) & quorum
    chosen_tick = jnp.where(newly_chosen, t, state.chosen_tick)
    replica_arrival = jnp.where(
        newly_chosen, t + _lat(cfg, k_lat3, (W,)), state.replica_arrival
    )
    status = jnp.where(newly_chosen, CHOSEN, status)
    latency = jnp.where(newly_chosen, t - state.propose_tick, 0)
    committed = state.committed + jnp.sum(newly_chosen)
    lat_sum = state.lat_sum + jnp.sum(latency)
    bins = jnp.clip(latency, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly_chosen.astype(jnp.int32), bins, LAT_BINS
    )

    # 3. Retire the contiguous chosen prefix that reached the replicas.
    slot_of_ord = state.head + w_iota
    pos_of_ord = slot_of_ord % W
    executable = (
        (status[pos_of_ord] == CHOSEN)
        & (replica_arrival[pos_of_ord] <= t)
        & (slot_of_ord < state.next_slot)
    )
    n_retire = jnp.sum(jnp.cumprod(executable.astype(jnp.int32)))
    ord_of_pos = (w_iota - state.head) % W
    retire = ord_of_pos < n_retire
    head = state.head + n_retire
    retired = state.retired + n_retire
    status = jnp.where(retire, EMPTY, status)
    chosen_tick = jnp.where(retire, INF, chosen_tick)
    replica_arrival = jnp.where(retire, INF, replica_arrival)
    propose_tick = jnp.where(retire, INF, state.propose_tick)
    last_send = jnp.where(retire, INF, state.last_send)
    p2a_arrival = jnp.where(retire[:, None, None], INF, state.p2a_arrival)
    p2b_arrival = jnp.where(retire[:, None, None], INF, p2b_arrival)

    # 4. Propose up to K new slots (one lane: the single grid log;
    # under a workload plan the static knob becomes the admission cap).
    space = W - (state.next_slot - head)
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, 1)
        adm = workload_mod.admission(wl, wls, wl_writes)
        count = jnp.minimum(adm[0], space)
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count[None],
            jnp.sum(newly_chosen)[None],
        )
    else:
        count = jnp.minimum(cfg.slots_per_tick, space)
    delta = (w_iota - state.next_slot) % W
    is_new = delta < count
    next_slot = state.next_slot + count
    status = jnp.where(is_new, PROPOSED, status)
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    if cfg.mode == "grid":
        # Thrifty write quorum: one random column per (slot, row)
        # (Grid.randomWriteQuorum generalized to per-row choices).
        col = jax.random.randint(k_col, (W, R), 0, C)
        in_quorum = jnp.arange(C)[None, None, :] == col[:, :, None]
    else:
        # Majority mode: thrifty = a random majority. Rank a PRNG score.
        scores = jax.random.uniform(k_col, (W, R * C))
        kth = jnp.sort(scores, axis=1)[:, cfg.majority_size - 1 : cfg.majority_size]
        in_quorum = (scores <= kth).reshape(W, R, C)
    send = is_new[:, None, None] & in_quorum
    p2a_arrival = jnp.where(
        send & p2a_del,
        t + p2a_lat,
        p2a_arrival,
    )

    # 5. Retry to the FULL grid on timeout.
    timed_out = (status == PROPOSED) & (t - last_send >= cfg.retry_timeout)
    resend = timed_out[:, None, None]
    if retry_del is not None:
        resend = resend & retry_del
    p2a_arrival = jnp.where(resend, t + retry_lat, p2a_arrival)
    last_send = jnp.where(timed_out, t, last_send)
    msgs_sent = (
        state.msgs_sent + jnp.sum(send) + jnp.sum(timed_out) * (R * C)
    )

    tel = record(
        state.telemetry,
        proposals=count,
        phase2_msgs=msgs_sent - state.msgs_sent,
        commits=committed - state.committed,
        executes=n_retire,
        retries=jnp.sum(timed_out),
        queue_depth=next_slot - head,
        queue_capacity=W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    return GridBatchedState(
        next_slot=next_slot,
        head=head,
        status=status,
        propose_tick=propose_tick,
        last_send=last_send,
        chosen_tick=chosen_tick,
        replica_arrival=replica_arrival,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        committed=committed,
        retired=retired,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        msgs_sent=msgs_sent,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(cfg, state, t0, num_ticks: int, key):
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(cfg: GridBatchedConfig, state: GridBatchedState, t) -> dict:
    """Device-side safety checks; returns traced boolean scalars (like
    every other backend) so the checks also run under jit/vmap — the
    simtest harness vmaps them over seed axes."""
    votes_in = state.p2b_arrival <= t
    chosen = state.status == CHOSEN
    if cfg.mode == "grid":
        quorum = jnp.all(jnp.any(votes_in, axis=2), axis=1)
    else:
        quorum = jnp.sum(votes_in, axis=(1, 2)) >= cfg.majority_size
    return {
        "quorum_ok": jnp.all(jnp.where(chosen, quorum, True)),
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "window_ok": (
            (state.head <= state.next_slot)
            & (state.next_slot - state.head <= cfg.window)
        ),
        "conserved": state.retired <= state.committed,
    }


def sweep(configs, num_ticks: int = 300, seed: int = 0):
    """Run several quorum configurations and report committed/sec-style
    stats for comparison (the grid-vs-majority sweep)."""
    results = []
    for cfg in configs:
        state = init_state(cfg)
        state, t = run_ticks(
            cfg, state, jnp.zeros((), jnp.int32), num_ticks, jax.random.PRNGKey(seed)
        )
        jax.block_until_ready(state)
        committed = int(state.committed)
        lat_hist = jax.device_get(state.lat_hist)
        cum = lat_hist.cumsum()
        p50 = int((cum >= max(1, (committed + 1) // 2)).argmax()) if committed else -1
        p99 = (
            int((cum >= max(1, -(-committed * 99 // 100))).argmax())
            if committed
            else -1
        )
        results.append(
            {
                "mode": cfg.mode,
                "acceptors": cfg.num_acceptors,
                "drop_rate": cfg.drop_rate,
                "committed": committed,
                "p50_latency_ticks": p50,
                "p99_latency_ticks": p99,
                "mean_latency_ticks": (
                    round(float(state.lat_sum) / committed, 2)
                    if committed
                    else -1.0
                ),
                "msgs_sent": int(state.msgs_sent),
                "msgs_per_commit": (
                    round(int(state.msgs_sent) / committed, 1)
                    if committed
                    else -1.0
                ),
                "invariants": {
                    k: bool(v)
                    for k, v in check_invariants(cfg, state, t).items()
                },
            }
        )
    return results


def main() -> None:
    """CLI: the flexible-quorum sweep (grid vs majority at increasing
    scale). Scale via argv: `python -m frankenpaxos_tpu.tpu.grid_batched
    [rows cols]` (defaults 10 10; the 100k-acceptor point is rows=cols=316
    on real TPU)."""
    import json
    import sys

    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    cols = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    # Lossless AND lossy points: exact thrifty quorums have zero loss
    # margin, so drops expose the modes' different retry economics.
    results = sweep(
        [
            GridBatchedConfig(rows=rows, cols=cols, mode=m, drop_rate=d)
            for m in ("grid", "majority")
            for d in (0.0, 0.05)
        ]
    )
    print(json.dumps(results, default=str))


if __name__ == "__main__":
    main()


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> GridBatchedConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return GridBatchedConfig(
        rows=3, cols=3, window=16, slots_per_tick=2,
        retry_timeout=8, faults=faults, workload=workload,
    )
