"""Bit-packed plane codecs: the dtype policy's sub-byte tier.

The PR 1 dtype policy (tpu/common.py) stopped at int8 — the narrowest
dtype XLA stores natively. But the hot narrow planes are narrower than
that: a slot status is one of three codes (2 bits), a session-table
occupancy flag is one bit. On a bandwidth-bound tick (the whole
simulation is elementwise sweeps over carried state) an int8 plane
still moves a full byte per 2-bit value, so the scan carry pays 4x the
bytes the information content demands. This module packs those planes
into int32 WORDS (the natural XLA storage/vector width): 16 status
codes or 32 occupancy bits per word, little-endian within the word.

Contract — the packed plane is a pure STORAGE transform:

  * ``unpack_*(pack_*(x)) == x`` exactly (values must fit their bit
    width; packers mask defensively).
  * Backends adopting a packed plane unpack ONCE at tick entry into
    the same local the unpacked twin reads, and pack ONCE at tick
    exit — every tick equation (and every kernel plane) sees the
    identical unpacked array, so packed runs are bit-identical to
    unpacked runs BY CONSTRUCTION (pinned 3-seed by
    ``tests/test_packing.py``). Only the scan-carry HBM traffic
    changes.
  * ALL bit-twiddling on packed planes lives HERE. The
    ``packing-containment`` analysis rule rejects raw shift/mask
    arithmetic on packed-plane fields (``common.PACKED_PLANES``)
    anywhere else in ``tpu/`` — the same single-dispatch-point
    discipline ``kernel-pallas-containment`` enforces for Pallas.
  * ``widen_state()`` passes packed words through untouched (they are
    int32 already): the widen twin of a packed run replays the packed
    program, and the packed-vs-unpacked comparison is pinned by its
    own twin tests instead.

The trace codec at the bottom serves the workload engine's
trace-driven open-loop mode (``WorkloadPlan(arrival="trace")``): one
int32 word per arrival event, ``(dt << 16) | lane`` — delta-encoded
ticks so a million-event trace is device-resident in 4 MB and replayed
by an in-graph cursor with no host round-trips.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import DTYPE_STATUS

# Bit widths of the packed planes (mirrored by common.PACKED_PLANES,
# the policy descriptor the analysis rule and the bench memory block
# read).
STATUS_BITS = 2  # EMPTY | PROPOSED | CHOSEN and the read-ring phases
OCC_BITS = 1  # session-table occupancy flags

_WORD_BITS = 32


def words_for(size: int, bits: int) -> int:
    """int32 words needed to pack ``size`` values of ``bits`` bits."""
    assert bits in (1, 2, 4, 8, 16) and size >= 0
    per = _WORD_BITS // bits
    return (size + per - 1) // per


def _as_u32(words: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.bitcast_convert_type(words, jnp.uint32)


def _as_i32(words: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def pack_plane(x: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Pack the LAST axis of a small-nonnegative-integer (or bool)
    plane into int32 words, ``32 // bits`` values per word,
    little-endian within the word (value ``i`` occupies bits
    ``[bits*(i % per), bits*(i % per + 1))`` of word ``i // per``).
    The tail word zero-pads. Values are masked to ``bits`` bits."""
    per = _WORD_BITS // bits
    size = x.shape[-1]
    nw = words_for(size, bits)
    mask = jnp.uint32((1 << bits) - 1)
    xu = x.astype(jnp.uint32) & mask
    pad = nw * per - size
    if pad:
        xu = jnp.pad(xu, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xu = xu.reshape(x.shape[:-1] + (nw, per))
    shifts = (jnp.arange(per, dtype=jnp.uint32) * bits).astype(jnp.uint32)
    # Disjoint bit fields: the sum IS the bitwise-or of the shifted
    # lanes, and XLA fuses it into the surrounding elementwise sweep.
    words = jnp.sum(xu << shifts, axis=-1, dtype=jnp.uint32)
    return _as_i32(words)


def unpack_plane(
    words: jnp.ndarray, bits: int, size: int, dtype=jnp.int32
) -> jnp.ndarray:
    """Inverse of :func:`pack_plane`: expand int32 words back to
    ``size`` values of ``dtype`` along the last axis."""
    per = _WORD_BITS // bits
    mask = jnp.uint32((1 << bits) - 1)
    wu = _as_u32(words)
    shifts = (jnp.arange(per, dtype=jnp.uint32) * bits).astype(jnp.uint32)
    vals = (wu[..., None] >> shifts) & mask
    vals = vals.reshape(words.shape[:-1] + (words.shape[-1] * per,))
    return vals[..., :size].astype(dtype)


# ---------------------------------------------------------------------------
# Status planes (2-bit codes, int8 unpacked twin)
# ---------------------------------------------------------------------------


def pack_status(status: jnp.ndarray) -> jnp.ndarray:
    """Pack an ``[..., W]`` status/phase plane (codes < 4) into
    ``[..., words_for(W, 2)]`` int32 words."""
    return pack_plane(status, STATUS_BITS)


def unpack_status(words: jnp.ndarray, size: int) -> jnp.ndarray:
    """Unpack a packed status plane back to its ``DTYPE_STATUS``
    (int8) twin — the array every tick equation and kernel plane
    reads, byte-identical to the unpacked backend's."""
    return unpack_plane(words, STATUS_BITS, size, DTYPE_STATUS)


# ---------------------------------------------------------------------------
# Occupancy bitmaps (1-bit liveness, bool unpacked twin)
# ---------------------------------------------------------------------------


def make_occ(lanes: int, size: int) -> jnp.ndarray:
    """An all-dead ``[lanes, words_for(size, 1)]`` occupancy bitmap."""
    return jnp.zeros((lanes, words_for(size, OCC_BITS)), jnp.int32)


def occ_unpack(occ: jnp.ndarray, size: int) -> jnp.ndarray:
    """``[..., size]`` bool liveness view of a packed bitmap."""
    return unpack_plane(occ, OCC_BITS, size, jnp.int32).astype(bool)


def occ_set(occ: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Set the bits where ``mask`` (``[..., size]`` bool) holds."""
    return _as_i32(_as_u32(occ) | _as_u32(pack_plane(mask, OCC_BITS)))


def occ_clear(occ: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Clear the bits where ``mask`` (``[..., size]`` bool) holds."""
    return _as_i32(_as_u32(occ) & ~_as_u32(pack_plane(mask, OCC_BITS)))


def occ_get(occ: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Per-lane single-bit gather: ``idx`` is ``[L]`` (one position per
    lane of an ``[L, words]`` bitmap); returns ``[L]`` bool."""
    word = jnp.take_along_axis(
        occ, (idx // _WORD_BITS)[:, None], axis=1
    )[:, 0]
    bit = (_as_u32(word) >> (idx % _WORD_BITS).astype(jnp.uint32)) & 1
    return bit.astype(bool)


# ---------------------------------------------------------------------------
# Trace codec (the workload engine's open-loop arrival trace)
# ---------------------------------------------------------------------------

# One event per int32 word: (delta-tick << 16) | lane. Both fields are
# 16-bit — inter-arrival gaps beyond 65535 ticks and lane axes beyond
# 65536 lanes need a wider codec than a million-session brick does.
TRACE_DT_BITS = 16
TRACE_LANE_MASK = (1 << TRACE_DT_BITS) - 1


def encode_trace(ticks, lane_ids):
    """HOST-side trace encoder: absolute arrival ``ticks``
    (nondecreasing) + ``lane_ids`` -> one int32 word per event,
    delta-encoded against the previous event (the first event's delta
    is its absolute tick). Returns a numpy int32 array sized for
    ``WorkloadState.trace``."""
    import numpy as np

    ticks = np.asarray(ticks, np.int64)
    lane_ids = np.asarray(lane_ids, np.int64)
    assert ticks.shape == lane_ids.shape and ticks.ndim == 1
    assert ticks.size > 0, "an empty trace has no arrival process"
    dts = np.diff(ticks, prepend=np.int64(0))
    assert (dts >= 0).all(), "trace ticks must be nondecreasing"
    assert (dts <= TRACE_LANE_MASK).all(), (
        "inter-arrival gap exceeds the 16-bit delta field"
    )
    assert (lane_ids >= 0).all() and (lane_ids <= TRACE_LANE_MASK).all()
    words = (dts.astype(np.uint32) << TRACE_DT_BITS) | lane_ids.astype(
        np.uint32
    )
    return words.view(np.int32)


def decode_trace(words: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """In-graph decoder: packed trace words -> ``(dts, lanes)`` int32
    pairs (delta ticks against the previous event, lane ids)."""
    wu = _as_u32(words)
    dts = (wu >> TRACE_DT_BITS).astype(jnp.int32)
    lanes = (wu & jnp.uint32(TRACE_LANE_MASK)).astype(jnp.int32)
    return dts, lanes


def trace_first_time(words) -> int:
    """HOST-side: the absolute tick of a trace's first event (what
    ``workload.load_trace`` seeds the in-graph cursor clock with)."""
    import numpy as np

    w0 = np.asarray(words, np.int32).reshape(-1)[0]
    return int(np.uint32(w0) >> TRACE_DT_BITS)
