"""TpuSimTransport: the user-facing handle on the batched TPU simulation.

The analog of constructing a cluster on a transport (SURVEY.md §1 L0):
where ``SimTransport`` delivers one message at a time under a Python
scheduler, ``TpuSimTransport`` advances the WHOLE cluster one tick at a
time as a compiled XLA program, with PRNG-sampled message latency and loss
standing in for the scheduler's nondeterminism. Exposes:

  * ``run(num_ticks)`` — advance the simulation (jit + lax.scan);
  * ``stats()`` — committed/executed counts, commit-latency p50/mean;
  * ``leader_change()`` — inject a leader failover (round bump + repair);
  * ``check_invariants()`` — device-side safety checks;
  * sharding over a device mesh via ``frankenpaxos_tpu.parallel``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.multipaxos_batched import (
    LAT_BINS,
    BatchedMultiPaxosConfig,
    BatchedMultiPaxosState,
    check_invariants,
    init_state,
    leader_change,
    reconfigure,
    run_ticks,
)


class TpuSimTransport:
    def __init__(
        self,
        config: BatchedMultiPaxosConfig,
        seed: int = 0,
        mesh: Optional[jax.sharding.Mesh] = None,
    ):
        self.config = config
        self.key = jax.random.PRNGKey(seed)
        self.t = jnp.zeros((), jnp.int32)
        self._epoch = 0
        self.mesh = mesh
        state = init_state(config)
        if mesh is not None:
            from frankenpaxos_tpu.parallel import shard_state

            state = shard_state(state, mesh)
        self.state = state

    def run(self, num_ticks: int) -> None:
        # run_ticks DONATES the state argument (single-buffered in device
        # memory); rebinding self.state to the returned state is the
        # donation contract — any alias of the previous self.state is
        # dead after this call.
        key = jax.random.fold_in(self.key, self._epoch)
        self._epoch += 1
        if self.mesh is not None:
            from frankenpaxos_tpu.parallel import run_ticks_sharded

            self.state, self.t = run_ticks_sharded(
                self.config, self.mesh, self.state, self.t, num_ticks, key
            )
        else:
            self.state, self.t = run_ticks(
                self.config, self.state, self.t, num_ticks, key
            )

    def leader_change(self) -> None:
        key = jax.random.fold_in(self.key, 10_000_000 + self._epoch)
        self._epoch += 1
        self.state = leader_change(self.config, self.state, self.t, key)

    def reconfigure(self) -> None:
        """Swap in a fresh acceptor configuration (Matchmaker churn)."""
        key = jax.random.fold_in(self.key, 20_000_000 + self._epoch)
        self._epoch += 1
        self.state = reconfigure(self.config, self.state, self.t, key)

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.state)

    def profile(self, num_ticks: int, trace_dir: str) -> str:
        """Run ``num_ticks`` under jax.profiler and write a trace into
        ``trace_dir`` (viewable in TensorBoard/Perfetto) — the device-side
        profiling capability the reference gets from perf-record flame
        graphs (``benchmarks/perf_util.py:37-96``)."""
        # Warm up with the SAME segment length: run_ticks specializes on
        # num_ticks, so a different warmup length would leave compilation
        # inside the trace.
        self.run(num_ticks)
        self.block_until_ready()
        with jax.profiler.trace(trace_dir):
            self.run(num_ticks)
            self.block_until_ready()
        return trace_dir

    # -- Observability -------------------------------------------------------

    def committed(self) -> int:
        return int(self.state.committed)

    def executed(self) -> int:
        return int(self.state.retired)

    def stats(self) -> dict:
        committed = int(self.state.committed)
        lat_hist = jax.device_get(self.state.lat_hist)
        cum = lat_hist.cumsum()
        p50 = int((cum >= max(1, (committed + 1) // 2)).argmax()) if committed else -1
        p99 = (
            int((cum >= max(1, -(-committed * 99 // 100))).argmax())
            if committed
            else -1
        )
        out = {
            "ticks": int(self.t),
            "committed": committed,
            "executed": int(self.state.retired),
            "commit_latency_mean_ticks": (
                float(self.state.lat_sum) / committed if committed else -1.0
            ),
            "commit_latency_p50_ticks": p50,
            "commit_latency_p99_ticks": p99,
            "round": int(jax.device_get(self.state.leader_round).max()),
            "num_acceptors": self.config.num_acceptors,
        }
        if self.config.fail_rate > 0.0 or self.config.device_elections:
            out["elections"] = int(self.state.elections)
            out["alive_leaders"] = int(
                jax.device_get(self.state.leader_alive).sum()
            )
        if self.config.reconfigure_every:
            out["reconfigurations"] = int(self.state.reconfigs)
            out["old_configs_gcd"] = int(self.state.configs_gcd)
            out["old_configs_live"] = int(
                jax.device_get(self.state.old_live).sum()
            )
            out["config_epoch_max"] = int(
                jax.device_get(self.state.config_epoch).max()
            )
        if self.config.state_machine != "none":
            out["sm_applied"] = int(self.state.sm_applied)
            out["dups_filtered"] = int(self.state.dups_filtered)
            out["kv_keys_set"] = int(
                (jax.device_get(self.state.kv_val) >= 0).sum()
            )
        if self.config.read_rate:
            reads = int(self.state.reads_done)
            rhist = jax.device_get(self.state.read_lat_hist)
            rcum = rhist.cumsum()
            out["reads_done"] = reads
            out["read_mode"] = self.config.read_mode
            out["read_latency_mean_ticks"] = (
                float(self.state.read_lat_sum) / reads if reads else -1.0
            )
            out["read_latency_p50_ticks"] = (
                int((rcum >= max(1, (reads + 1) // 2)).argmax()) if reads else -1
            )
            out["reads_shed"] = int(self.state.reads_shed)
        return out

    def check_invariants(self) -> dict:
        return {
            k: bool(v)
            for k, v in check_invariants(self.config, self.state, self.t).items()
        }
