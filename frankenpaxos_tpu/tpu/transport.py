"""TpuSimTransport: the user-facing handle on the batched TPU simulation.

The analog of constructing a cluster on a transport (SURVEY.md §1 L0):
where ``SimTransport`` delivers one message at a time under a Python
scheduler, ``TpuSimTransport`` advances the WHOLE cluster one tick at a
time as a compiled XLA program, with PRNG-sampled message latency and loss
standing in for the scheduler's nondeterminism. Exposes:

  * ``run(num_ticks)`` — advance the simulation (jit + lax.scan);
  * ``stats()`` — committed/executed counts, commit-latency p50/mean,
    pulled as ONE coalesced device transfer;
  * ``telemetry()`` — the in-graph per-tick metric ring
    (``tpu/telemetry.py``), one coalesced transfer at epoch boundaries
    (zero host sync happened inside the tick loop to produce it);
    ``telemetry_series()/_summary()/_dict()`` host views;
  * ``trace()`` — host-side wall-clock spans around compile/dispatch/
    wait/transfer (the ``fpx_host_*`` half of the exposition scheme);
  * ``leader_change()`` — inject a leader failover (round bump + repair);
  * ``check_invariants()`` — device-side safety checks;
  * sharding over a device mesh via ``frankenpaxos_tpu.parallel``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import List, Optional

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    LAT_BINS,
    BatchedMultiPaxosConfig,
    BatchedMultiPaxosState,
    check_invariants,
    init_state,
    leader_change,
    reconfigure,
    run_ticks,
)


class TpuSimTransport:
    def __init__(
        self,
        config: BatchedMultiPaxosConfig,
        seed: int = 0,
        mesh: Optional[jax.sharding.Mesh] = None,
        telemetry_window: Optional[int] = None,
        telemetry_spans: int = 0,
    ):
        self.config = config
        self.key = jax.random.PRNGKey(seed)
        self.t = jnp.zeros((), jnp.int32)
        self._epoch = 0
        self.mesh = mesh
        # Host-side trace spans (the fpx_host_* half of the unified
        # naming scheme): wall-clock stamped compile/dispatch/wait/
        # transfer records, appended by _span below.
        self.trace_spans: List[dict] = []
        self._dispatched_lengths: set = set()
        state = init_state(config)
        if telemetry_window is not None or telemetry_spans:
            window = (
                telemetry_window
                if telemetry_window is not None
                else telemetry_mod.TELEM_WINDOW
            )
            state = dataclasses.replace(
                state,
                telemetry=telemetry_mod.make_telemetry(
                    window, spans=telemetry_spans
                ),
            )
        if mesh is not None:
            from frankenpaxos_tpu.parallel import shard_state

            state = shard_state(state, mesh)
        self.state = state

    @contextlib.contextmanager
    def _span(self, name: str, **meta):
        """Record one host-side trace span (unix wall-clock stamped)."""
        start = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.trace_spans.append(
                {
                    "name": name,
                    "start_unix": start,
                    "duration_s": time.perf_counter() - t0,
                    **meta,
                }
            )

    def trace(self) -> List[dict]:
        """The recorded host-side spans; clear with ``trace_spans.clear()``.
        Dispatch spans on a segment length not seen before include the
        XLA compile (``compile=True``) — JAX dispatch is async, so
        device execution itself lands in the following wait/transfer
        span, not here."""
        return list(self.trace_spans)

    def run(self, num_ticks: int) -> None:
        # run_ticks DONATES the state argument (single-buffered in device
        # memory); rebinding self.state to the returned state is the
        # donation contract — any alias of the previous self.state is
        # dead after this call.
        key = jax.random.fold_in(self.key, self._epoch)
        self._epoch += 1
        compiling = num_ticks not in self._dispatched_lengths
        self._dispatched_lengths.add(num_ticks)
        with self._span(
            "dispatch", num_ticks=num_ticks, compile=compiling
        ):
            if self.mesh is not None:
                from frankenpaxos_tpu.parallel import run_ticks_sharded

                self.state, self.t = run_ticks_sharded(
                    self.config, self.mesh, self.state, self.t, num_ticks,
                    key,
                )
            else:
                self.state, self.t = run_ticks(
                    self.config, self.state, self.t, num_ticks, key
                )

    def leader_change(self) -> None:
        key = jax.random.fold_in(self.key, 10_000_000 + self._epoch)
        self._epoch += 1
        self.state = leader_change(self.config, self.state, self.t, key)

    def reconfigure(self) -> None:
        """Swap in a fresh acceptor configuration (Matchmaker churn)."""
        key = jax.random.fold_in(self.key, 20_000_000 + self._epoch)
        self._epoch += 1
        self.state = reconfigure(self.config, self.state, self.t, key)

    def block_until_ready(self) -> None:
        with self._span("wait"):
            jax.block_until_ready(self.state)

    def profile(self, num_ticks: int, trace_dir: str) -> str:
        """Run ``num_ticks`` under jax.profiler and write a trace into
        ``trace_dir`` (viewable in TensorBoard/Perfetto) — the device-side
        profiling capability the reference gets from perf-record flame
        graphs (``benchmarks/perf_util.py:37-96``)."""
        # Warm up with the SAME segment length: run_ticks specializes on
        # num_ticks, so a different warmup length would leave compilation
        # inside the trace.
        self.run(num_ticks)
        self.block_until_ready()
        with jax.profiler.trace(trace_dir):
            self.run(num_ticks)
            self.block_until_ready()
        return trace_dir

    # -- Observability -------------------------------------------------------

    def committed(self) -> int:
        return int(self.state.committed)

    def executed(self) -> int:
        return int(self.state.retired)

    def stats(self) -> dict:
        # ONE coalesced jax.device_get of the stats sub-pytree. The old
        # implementation issued a separate blocking transfer per field
        # (each int()/device_get call is its own round trip — a dozen+
        # host syncs per stats() call); batching them into a single dict
        # pull makes stats() one transfer regardless of which optional
        # subsystems are live.
        st = self.state
        dev = {
            "committed": st.committed,
            "retired": st.retired,
            "lat_sum": st.lat_sum,
            "lat_hist": st.lat_hist,
            "round_max": st.leader_round.max(),
            "t": self.t,
        }
        if (
            self.config.fail_rate > 0.0
            or self.config.device_elections
            or self.config.faults.crash_rate > 0.0
        ):
            dev["elections"] = st.elections
            dev["alive_leaders"] = st.leader_alive.sum()
        if self.config.reconfigure_every:
            dev["reconfigs"] = st.reconfigs
            dev["configs_gcd"] = st.configs_gcd
            dev["old_live"] = st.old_live.sum()
            dev["config_epoch_max"] = st.config_epoch.max()
        if self.config.state_machine != "none":
            dev["sm_applied"] = st.sm_applied
            dev["dups_filtered"] = st.dups_filtered
            dev["kv_keys_set"] = (st.kv_val >= 0).sum()
        if self.config.read_rate:
            dev["reads_done"] = st.reads_done
            dev["read_lat_sum"] = st.read_lat_sum
            dev["read_lat_hist"] = st.read_lat_hist
            dev["reads_shed"] = st.reads_shed
        with self._span("transfer", what="stats"):
            host = jax.device_get(dev)

        committed = int(host["committed"])
        cum = host["lat_hist"].cumsum()
        p50 = int((cum >= max(1, (committed + 1) // 2)).argmax()) if committed else -1
        p99 = (
            int((cum >= max(1, -(-committed * 99 // 100))).argmax())
            if committed
            else -1
        )
        out = {
            "ticks": int(host["t"]),
            "committed": committed,
            "executed": int(host["retired"]),
            "commit_latency_mean_ticks": (
                float(host["lat_sum"]) / committed if committed else -1.0
            ),
            "commit_latency_p50_ticks": p50,
            "commit_latency_p99_ticks": p99,
            "round": int(host["round_max"]),
            "num_acceptors": self.config.num_acceptors,
        }
        if (
            self.config.fail_rate > 0.0
            or self.config.device_elections
            or self.config.faults.crash_rate > 0.0
        ):
            out["elections"] = int(host["elections"])
            out["alive_leaders"] = int(host["alive_leaders"])
        if self.config.reconfigure_every:
            out["reconfigurations"] = int(host["reconfigs"])
            out["old_configs_gcd"] = int(host["configs_gcd"])
            out["old_configs_live"] = int(host["old_live"])
            out["config_epoch_max"] = int(host["config_epoch_max"])
        if self.config.state_machine != "none":
            out["sm_applied"] = int(host["sm_applied"])
            out["dups_filtered"] = int(host["dups_filtered"])
            out["kv_keys_set"] = int(host["kv_keys_set"])
        if self.config.read_rate:
            reads = int(host["reads_done"])
            rcum = host["read_lat_hist"].cumsum()
            out["reads_done"] = reads
            out["read_mode"] = self.config.read_mode
            out["read_latency_mean_ticks"] = (
                float(host["read_lat_sum"]) / reads if reads else -1.0
            )
            out["read_latency_p50_ticks"] = (
                int((rcum >= max(1, (reads + 1) // 2)).argmax()) if reads else -1
            )
            out["reads_shed"] = int(host["reads_shed"])
        return out

    def telemetry(self) -> "telemetry_mod.Telemetry":
        """The device-side per-tick metric ring (tpu/telemetry.py), as
        ONE coalesced transfer at the epoch boundary. Zero host sync
        happened inside the tick loop to produce it — but this pull
        itself synchronizes on any in-flight run() (device_get waits
        for pending work on the state), so call it between segments,
        not to overlap with one."""
        with self._span("transfer", what="telemetry"):
            return telemetry_mod.fetch(self.state.telemetry)

    def telemetry_series(self) -> dict:
        """Chronological per-tick series over the retained ring."""
        return telemetry_mod.series(self.telemetry())

    def telemetry_summary(self) -> dict:
        return telemetry_mod.summary(self.telemetry())

    def telemetry_dict(self) -> dict:
        """JSON-serializable capture (the dashboard interchange format)."""
        return telemetry_mod.to_dict(self.telemetry())

    def check_invariants(self) -> dict:
        return {
            k: bool(v)
            for k, v in check_invariants(self.config, self.state, self.t).items()
        }
