"""Batched Bipartisan Paxos (BPaxos) as a single XLA program — the
dependency-graph protocol family on the device-side executor.

BPaxos (PAPERS: arXiv 2003.00331) is state machine replication DISAGGREGATED
into single-purpose modules: leaderless PROPOSERS take client commands,
a DEPENDENCY SERVICE computes each command's conflict set, per-vertex
CONSENSUS (one Paxos instance per (leader, index) vertex) makes the
``(command, deps)`` pair durable, and REPLICAS execute the resulting
dependency graph — eligible strongly-connected components in reverse
topological order (``bpaxos/DependencyGraph.scala``). The modules scale
independently; the graph is the protocol.

TPU-first redesign, one plane per module:

  * PROPOSER plane: ``L`` leader lanes, each owning a ring of ``W``
    in-flight vertices (vertex id = lane * W + ring slot — the bounded
    (leader, index) instance space). Up to ``K`` commands per lane per
    tick, shaped by the workload engine (lane = the Zipf axis: hot-key
    skew piles arrivals — and therefore conflicts — onto lane 0).
  * DEP-SERVICE plane: the conflict relation drawn at propose time as
    ADJACENCY ROWS of the ``[V, V/32]`` uint32 bitmask
    (``ops/depgraph.py`` owns the packing). Every vertex depends on its
    own-lane predecessor (a leader serializes its lane), and on each
    LIVE vertex of another lane with probability ``conflict_rate`` —
    including vertices proposed the SAME tick, whose mutual draws are
    exactly the interfering-command races that create SCC cycles in the
    real protocol. The knob is traced when the workload plan carries
    ``conflict_rate`` (``workload.conflict_k16``): the whole
    [conflict x load] surface is ONE compile.
  * CONSENSUS plane: per-vertex commit latency = dep-service RTT +
    Paxos accept RTT + the replica broadcast hop, sampled per vertex;
    the unified fault layer stretches it (TCP retransmit semantics) and
    a LEADER-axis partition defers cut lanes' commits to the heal tick.
  * REPLICA plane: ``R`` executing replicas, each seeing a commit at
    its own broadcast-delayed tick (``rep_commit_tick``). Each tick
    every replica runs the ``depgraph_execute`` plane over the SHARED
    adjacency with its OWN (committed, active) view — a [R, V, V/32]
    batched closure, the kernel's natural batch axis (and the mesh
    shard axis: ``parallel/sharding.py`` tiles replicas over devices).
    Eligibility is closed under dependencies and own-lane chain edges
    make it a per-lane PREFIX, so each replica's executed state is just
    a [L] watermark (``head_r``); slots retire — and their adjacency
    rows AND columns clear — once every replica has executed them
    (``gc_head = min_r head_r``), which is what makes ring-slot reuse
    safe in a bounded window.

The dep-graph SAFETY claim (no instance executes before its committed
dependencies) is checked two ways: in-graph every tick
(``check_invariants``'s ``dep_safety_ok`` audits executed vertices' dep
rows via ``depgraph.rows_subset``) and against the host Tarjan oracle in
``tests/test_tpu_bpaxos.py`` / ``harness/simtest.py``'s randomized
[faults x conflict-rate] schedules.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import INF, LAT_BINS, sample_latency
# Submodule imports (package-attr access on frankenpaxos_tpu.ops would
# be circular during tpu package init). Importing ops.depgraph is what
# registers the `depgraph_execute` plane before the first dispatch.
from frankenpaxos_tpu.ops import depgraph as depgraph_mod
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record


@dataclasses.dataclass(frozen=True)
class BatchedBPaxosConfig:
    """Static (compile-time) simulation parameters."""

    num_leaders: int = 3  # L: leaderless proposer lanes
    window: int = 32  # W: in-flight vertices per lane (ring capacity)
    cmds_per_tick: int = 2  # K: new commands per lane per tick
    lat_min: int = 1  # one-way message latency in ticks (uniform sample)
    lat_max: int = 3
    # P(a new command conflicts with a given live command of another
    # lane) — the dependency-graph edge density. Quantized to multiples
    # of 1/16 by the bit-sliced sampler; a WorkloadPlan carrying
    # ``conflict_rate`` overrides this with a TRACED value (the
    # [conflict x load] sweep axis).
    conflict_rate: float = 0.25
    # Module fan-outs (message accounting + the consensus RTT hops).
    num_dep_nodes: int = 3  # dependency-service nodes per command
    num_acceptors: int = 3  # per-vertex Paxos acceptors
    num_replicas: int = 4  # R: executing replicas (the plane batch axis)
    # Closed workload: stop proposing once each lane has allocated this
    # many vertices (None = open workload).
    max_cmds_per_leader: Optional[int] = None
    # Kernel-layer dispatch policy (ops/registry.py): the batched
    # dependency-graph closure — eligibility, SCC roots, deterministic
    # execution order for all R replica views at once — routes through
    # ops.registry.dispatch as `depgraph_execute`.
    kernels: KernelPolicy = KernelPolicy()
    # Unified in-graph fault injection (tpu/faults.py): the commit round
    # is modeled end-to-end, so drops/jitter stretch it and a
    # LEADER-axis partition defers cut lanes' commits to the heal tick
    # (dependency chains through the cut lane stall at every replica
    # until then). FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-lane
    # command admission (bounded by cmds_per_tick; the FIFO backlog
    # carries the rest). Completions are command commits.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def num_vertices(self) -> int:
        return self.num_leaders * self.window

    def __post_init__(self):
        assert self.num_leaders >= 2
        assert self.window >= 2 * self.cmds_per_tick
        self.workload.validate()
        self.kernels.validate()
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.conflict_rate <= 1.0
        # The bit-sliced sampler quantizes to 16ths; a rate that
        # silently degrades to 0 or 1 would simulate a different
        # conflict regime (same contract as epaxos.see_same_tick_rate).
        k16 = round(self.conflict_rate * 16)
        assert (k16 == 0) == (self.conflict_rate == 0.0) and (
            k16 == 16
        ) == (self.conflict_rate == 1.0), (
            f"conflict_rate={self.conflict_rate} quantizes to "
            f"{k16}/16; pick a multiple of 1/16 (or >= 1/32) instead"
        )
        assert self.num_dep_nodes >= 1
        assert self.num_acceptors >= 1
        assert self.num_replicas >= 1
        self.faults.validate(axis=self.num_leaders)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedBPaxosState:
    """Struct-of-arrays vertex state. Shapes: [L] lanes, [L, W] ring
    vertices, [V, VW] packed adjacency (V = L*W, VW = ceil(V/32)),
    [R, ...] per-replica views."""

    next_cmd: jnp.ndarray  # [L] next per-lane command number
    gc_head: jnp.ndarray  # [L] lowest unretired command number
    # (= min over replicas of head_r: every slot below it has executed
    # everywhere, so its ring cell and adjacency row/column are clear)
    head_r: jnp.ndarray  # [R, L] per-replica executed watermark

    proposed: jnp.ndarray  # [L, W] ring slot holds a live vertex
    propose_tick: jnp.ndarray  # [L, W] proposal tick (INF = empty)
    commit_tick: jnp.ndarray  # [L, W] consensus-chosen tick (INF = empty)
    committed: jnp.ndarray  # [L, W] bool: the commit is durable
    rep_commit_tick: jnp.ndarray  # [R, L, W] tick the commit REACHES
    # each replica (broadcast hop; INF = empty)
    # The dependency graph itself: row v's bits are the vertices v
    # depends on (ops/depgraph.py owns every bit-level operation).
    adj: jnp.ndarray  # [V, VW] uint32 packed adjacency

    # Stats.
    committed_total: jnp.ndarray  # [] cumulative commits (global)
    executed_total: jnp.ndarray  # [] cumulative per-replica executions
    retired_total: jnp.ndarray  # [] cumulative retired ring slots
    coexecuted: jnp.ndarray  # [] replica-0 executions that shared their
    # closure pass with an SCC partner (>= 2 members on one scc_root)
    lat_sum: jnp.ndarray  # [] sum of replica-0 propose->execute latencies
    lat_hist: jnp.ndarray  # [LAT_BINS] replica-0 execute latency histogram
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedBPaxosConfig) -> BatchedBPaxosState:
    L, W, R = cfg.num_leaders, cfg.window, cfg.num_replicas
    V = cfg.num_vertices
    VW = depgraph_mod.num_words(V)
    return BatchedBPaxosState(
        next_cmd=jnp.zeros((L,), jnp.int32),
        gc_head=jnp.zeros((L,), jnp.int32),
        head_r=jnp.zeros((R, L), jnp.int32),
        proposed=jnp.zeros((L, W), bool),
        propose_tick=jnp.full((L, W), INF, jnp.int32),
        commit_tick=jnp.full((L, W), INF, jnp.int32),
        committed=jnp.zeros((L, W), bool),
        rep_commit_tick=jnp.full((R, L, W), INF, jnp.int32),
        adj=jnp.zeros((V, VW), jnp.uint32),
        committed_total=jnp.zeros((), jnp.int32),
        executed_total=jnp.zeros((), jnp.int32),
        retired_total=jnp.zeros((), jnp.int32),
        coexecuted=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_leaders, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def _abs_slot(base: jnp.ndarray, W: int) -> jnp.ndarray:
    """[L, W] absolute command number at each ring position, valid for
    every cell occupied while ``base`` is the retire watermark."""
    w_iota = jnp.arange(W, dtype=jnp.int32)
    return base[:, None] + jnp.mod(w_iota[None, :] - base[:, None], W)


def tick(
    cfg: BatchedBPaxosConfig,
    state: BatchedBPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedBPaxosState:
    """One simulation tick: commits land per replica, every replica runs
    the dependency-graph closure plane and executes its eligible prefix,
    fully-executed slots retire (adjacency rows AND columns clear), and
    proposers admit new commands with dep-service-drawn conflict edges
    and consensus-sampled commit latencies."""
    L, W, R = cfg.num_leaders, cfg.window, cfg.num_replicas
    V = cfg.num_vertices
    VW = depgraph_mod.num_words(V)
    K = cfg.cmds_per_tick
    k_conf, k_lat, k_rep = jax.random.split(key, 3)
    w_iota = jnp.arange(W, dtype=jnp.int32)
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)

    # ---- 1. Commits land. Globally (consensus chose the vertex — the
    # stats/telemetry view) and per replica (the broadcast arrived —
    # what execution at that replica may act on).
    landing = state.proposed & (state.commit_tick <= t)
    committed = state.committed | landing
    new_commit_mask = committed & ~state.committed
    n_new_commits = jnp.sum(new_commit_mask)
    com_r = state.proposed[None] & (state.rep_commit_tick <= t)  # [R, L, W]

    # ---- 2. REPLICA plane: every replica runs the batched closure
    # over the SHARED graph with its OWN (committed, active) view.
    # active = live and not yet executed BY THIS replica; a dependency
    # on an inactive vertex is satisfied (this replica already executed
    # it, or it retired everywhere).
    abs_now = _abs_slot(state.gc_head, W)  # [L, W]
    act_r = state.proposed[None] & (
        abs_now[None] >= state.head_r[:, :, None]
    )  # [R, L, W]
    adj_b = jnp.broadcast_to(state.adj, (R, V, VW))
    eligible_b, _order_b, root_b = ops_registry.dispatch(
        "depgraph_execute",
        cfg,
        adj_b,
        com_r.reshape(R, V),
        act_r.reshape(R, V),
    )
    eligible_r = eligible_b.reshape(R, L, W)
    # Own-lane chain edges make each replica's eligible set a per-lane
    # PREFIX from head_r; the cumprod run is the executed advance.
    pos_of_ord = jnp.mod(
        state.head_r[:, :, None] + w_iota[None, None, :], W
    )  # [R, L, W]
    elig_ord = jnp.take_along_axis(eligible_r, pos_of_ord, axis=2)
    run_r = jnp.sum(
        jnp.cumprod(elig_ord.astype(jnp.int32), axis=2), axis=2
    )  # [R, L]
    head_r = state.head_r + run_r
    executed_total = state.executed_total + jnp.sum(run_r)

    # Replica-0 accounting: execute latency, and SCC co-execution (>= 2
    # newly executed members sharing one scc_root — the closure pass
    # committed a cycle together, the case the plane exists for).
    newly0 = (
        state.proposed
        & (abs_now >= state.head_r[0][:, None])
        & (abs_now < head_r[0][:, None])
    )  # [L, W]
    newly0_v = newly0.reshape(V)
    root0 = root_b[0]  # [V]
    members = jax.ops.segment_sum(
        newly0_v.astype(jnp.int32),
        jnp.where(newly0_v, root0, V),
        num_segments=V + 1,
    )
    in_scc = newly0_v & (
        jnp.take(members, jnp.where(newly0_v, root0, 0)) >= 2
    )
    coexecuted = state.coexecuted + jnp.sum(in_scc)
    lat = jnp.where(newly0, t - state.propose_tick, 0)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly0.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )

    # ---- 3. Retire (GC): slots every replica has executed leave the
    # ring; their adjacency row AND column bits clear (clear_vertices —
    # a stale column bit would fabricate a dependency on the slot's
    # next tenant).
    gc_head = jnp.min(head_r, axis=0)  # [L]
    run_gc = gc_head - state.gc_head
    retired_total = state.retired_total + jnp.sum(run_gc)
    ordinal_gc = jnp.mod(w_iota[None, :] - state.gc_head[:, None], W)
    clear = ordinal_gc < run_gc[:, None]  # [L, W]
    adj = depgraph_mod.clear_vertices(state.adj, clear.reshape(V))
    proposed = state.proposed & ~clear
    committed = committed & ~clear
    propose_tick = jnp.where(clear, INF, state.propose_tick)
    commit_tick = jnp.where(clear, INF, state.commit_tick)
    rep_commit_tick = jnp.where(clear[None], INF, state.rep_commit_tick)

    # ---- 4. PROPOSER plane: up to K new commands per lane if the ring
    # has room, shaped by workload admission.
    space = W - (state.next_cmd - gc_head)
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, L)
        adm = workload_mod.admission(wl, wls, wl_writes)
        count = jnp.minimum(jnp.minimum(adm, K), space)
    else:
        count = jnp.minimum(K, space)
    if cfg.max_cmds_per_leader is not None:
        count = jnp.minimum(
            count,
            jnp.maximum(cfg.max_cmds_per_leader - state.next_cmd, 0),
        )
    if wl.active:
        # Accounted AFTER every clamp: finish() must see the ACTUAL
        # per-lane issue count, or the backlog drains entries the ring
        # never admitted.
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count,
            jnp.sum(new_commit_mask, axis=1),
        )
    delta = jnp.mod(w_iota[None, :] - state.next_cmd[:, None], W)
    is_new = delta < count[:, None]
    next_cmd = state.next_cmd + count
    abs_new = state.next_cmd[:, None] + delta  # [L, W] new command nums

    # ---- 5. DEP-SERVICE plane: the new vertices' adjacency rows.
    # (a) Own-lane chain edge to the immediate predecessor, unless it
    # already retired everywhere (then the dependency is vacuous — and
    # its ring slot may already host a FUTURE vertex, so no bit).
    v_iota = jnp.arange(V, dtype=jnp.int32)
    lane_of_v = v_iota // W
    prev_id = (
        jnp.arange(L, dtype=jnp.int32)[:, None] * W
        + jnp.mod(w_iota[None, :] - 1, W)
    )  # [L, W] vertex id of the predecessor slot
    chain_ok = abs_new - 1 >= gc_head[:, None]  # [L, W]
    chain_bool = (
        (v_iota[None, None, :] == prev_id[:, :, None])
        & chain_ok[:, :, None]
    )  # [L, W, V]
    chain_words = depgraph_mod.pack_mask(chain_bool)  # [L, W, VW]
    # (b) Conflict edges: Bernoulli(conflict) per live OTHER-lane
    # vertex, drawn K-shaped (the full-ring draw would dominate the
    # tick at wide V) and gathered onto ring positions via delta.
    # "Live" includes vertices proposed THIS tick — mutual same-tick
    # draws are the SCC-forming races. The knob is traced when the
    # workload plan carries conflict_rate.
    k16 = workload_mod.conflict_k16(wl, wls, cfg.conflict_rate)
    sees_k = depgraph_mod.bernoulli_words_k16(k_conf, k16, (L, K, VW))
    live_after = (proposed | is_new).reshape(V)  # [V]
    live_words = depgraph_mod.pack_mask(live_after)  # [VW]
    own_lane_words = depgraph_mod.pack_mask(
        lane_of_v[None, :] == jnp.arange(L, dtype=jnp.int32)[:, None]
    )  # [L, VW]
    sees_k = sees_k & live_words[None, None, :] & ~own_lane_words[:, None, :]
    sees = jnp.take_along_axis(
        sees_k, jnp.clip(delta, 0, K - 1)[:, :, None], axis=1
    )  # [L, W, VW]
    new_rows = (chain_words | sees).reshape(V, VW)
    adj = jnp.where(is_new.reshape(V)[:, None], new_rows, adj)

    # ---- 6. CONSENSUS plane: commit latency = dep-service RTT (2
    # one-way hops) + Paxos accept RTT (2) + the replica broadcast hop
    # the per-replica arrival adds below. Faults stretch the round
    # end-to-end; a cut leader lane's commits defer to the heal tick.
    commit_lat = jnp.sum(
        sample_latency(cfg.lat_min, cfg.lat_max, k_lat, (4, L, W)),
        axis=0,
    )  # [L, W]
    if fp.traced or fp.drop_rate > 0.0 or fp.jitter > 0:
        commit_lat = faults_mod.tcp_latency(
            fp, faults_mod.fault_key(key), (L, W), commit_lat,
            rates=frates,
        )
    commit_arr = t + commit_lat
    if fp.has_partition:
        cut_lane = (~faults_mod.partition_row(fp, t, L))[:, None]
        commit_arr = faults_mod.defer_to_heal(fp, commit_arr, cut_lane)
    # Per-replica arrival: the commit broadcast hop, sampled per
    # replica (replica skew is what makes head_r a vector).
    rep_arr = commit_arr[None] + sample_latency(
        cfg.lat_min, cfg.lat_max, k_rep, (R, L, W)
    )  # [R, L, W]
    proposed = proposed | is_new
    propose_tick = jnp.where(is_new, t, propose_tick)
    commit_tick = jnp.where(is_new, commit_arr, commit_tick)
    rep_commit_tick = jnp.where(is_new[None], rep_arr, rep_commit_tick)
    committed = committed & ~is_new

    # ---- 7. Telemetry: dep-service + acceptor + replica fan-outs are
    # the phase-2 message plane (BPaxos is leaderless — no phase 1).
    n_new = jnp.sum(is_new)
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase2_msgs=(
            cfg.num_dep_nodes + cfg.num_acceptors + R
        ) * n_new,
        commits=n_new_commits,
        executes=jnp.sum(run_r[0]),
        queue_depth=jnp.sum(next_cmd - gc_head),
        queue_capacity=L * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )
    # Span sampler (telemetry.record_spans — the generic plumbing):
    # vertex lifecycles on the per-lane rings. Mapping: group = leader
    # lane, slot id = the command number at each ring position (OLD
    # gc_head — valid for every cell occupied at tick start, including
    # this tick's retirees); a cell proposed THIS tick carries the OLD
    # next_cmd number. Consensus choice is one event (vote == chosen);
    # the "executed" stamp is ring retirement (all replicas executed).
    # No phase-1 plane: BPaxos proposers are leaderless. Structurally
    # OFF at spans=0, like the counter ring.
    if telemetry_mod.span_slots(tel):
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=is_new,
            slot_ids=abs_now,
            new_slot_ids=abs_new,
            phase1_mark=jnp.zeros((L,), bool),
            voted=new_commit_mask,
            newly_chosen=new_commit_mask,
            retire_mask=clear,
        )

    return BatchedBPaxosState(
        next_cmd=next_cmd,
        gc_head=gc_head,
        head_r=head_r,
        proposed=proposed,
        propose_tick=propose_tick,
        commit_tick=commit_tick,
        committed=committed,
        rep_commit_tick=rep_commit_tick,
        adj=adj,
        committed_total=state.committed_total + n_new_commits,
        executed_total=executed_total,
        retired_total=retired_total,
        coexecuted=coexecuted,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedBPaxosConfig,
    state: BatchedBPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedBPaxosState, jnp.ndarray]:
    """Run ``num_ticks`` ticks under lax.scan; returns (state, t0+num_ticks)."""

    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedBPaxosConfig, state: BatchedBPaxosState, t
) -> dict:
    """Device-side safety checks; all returned booleans must be True."""
    W = cfg.window
    V = cfg.num_vertices
    # Execution is per-lane prefix at every replica, so the cumulative
    # counter is exactly the total watermark advance.
    conserved = state.executed_total == jnp.sum(state.head_r)
    workload_ok = workload_mod.invariants_ok(cfg.workload, state.workload)
    # A replica only executes commits it has seen; commits are global
    # events counted once.
    books_ok = jnp.all(
        jnp.sum(state.head_r, axis=1) <= state.committed_total
    )
    retired_ok = state.retired_total == jnp.sum(state.gc_head)
    # Window bookkeeping: bounded state around the retire watermark.
    window_ok = (
        jnp.all(
            (state.gc_head[None] <= state.head_r)
            & (state.head_r <= state.next_cmd[None])
        )
        & jnp.all(state.next_cmd - state.gc_head <= W)
    )
    # Committed implies proposed (a commit can only land on a live slot).
    ring_ok = jnp.all(~state.committed | state.proposed)
    # THE dep-graph safety invariant: no vertex executed before its
    # committed dependencies. For every replica, each vertex it has
    # executed (live, abs < head_r) must have an adjacency row pointing
    # ONLY at vertices that replica also executed (bits to retired
    # vertices were cleared with them; bits to unexecuted ones would be
    # an ordering violation).
    abs_v = _abs_slot(state.gc_head, W).reshape(V)  # [V]
    lane_of_v = jnp.arange(V, dtype=jnp.int32) // W
    head_per_v = state.head_r[:, lane_of_v]  # [R, V]
    exec_r = state.proposed.reshape(V)[None, :] & (
        abs_v[None, :] < head_per_v
    )  # [R, V]
    deps_ok_rows = depgraph_mod.rows_subset(
        state.adj[None], depgraph_mod.pack_mask(exec_r)
    )  # [R, V]
    dep_safety_ok = jnp.all(~exec_r | deps_ok_rows)
    # Per-replica commit visibility never precedes the global commit.
    vis_ok = jnp.all(state.rep_commit_tick >= state.commit_tick[None])
    return {
        "conserved": conserved,
        "workload_ok": workload_ok,
        "books_ok": books_ok,
        "retired_ok": retired_ok,
        "window_ok": window_ok,
        "ring_ok": ring_ok,
        "dep_safety_ok": dep_safety_ok,
        "vis_ok": vis_ok,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedBPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every module plane (V = 48 vertices, 2 packed words, 4
    replicas so the mesh leg shards 2-way), small enough to trace and
    compile in well under a second."""
    return BatchedBPaxosConfig(
        num_leaders=3, window=16, cmds_per_tick=2, num_replicas=4,
        conflict_rate=0.25, faults=faults, workload=workload,
    )
