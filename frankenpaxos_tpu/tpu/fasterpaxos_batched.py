"""Batched Faster Paxos as a single XLA program: DELEGATE
slot-partitioning (reference ``fasterpaxos/Server.scala:315-340``
delegate indexes, ``:497-530`` dead-delegate leader change; per-actor
analog ``protocols/fasterpaxos.py``).

The defining mechanism: after phase 1, the leader grants ``f + 1``
DELEGATES proposal rights over the log, partitioned round-robin — seat
``d`` owns slots ``{o : o mod D == d}`` — so clients commit through
their delegate in one round trip without the leader on the critical
path (Phase2aAny). The cost: a dead delegate stalls its stripe of the
log (the execution watermark is the min over seats), and the repair is
a LEADER CHANGE — a higher round, phase 1 against the servers, a fresh
delegate seating that excludes the dead server, and re-proposal of
everything in flight.

TPU-first layout: ``G`` independent groups, each with ``S = 2f+1``
servers (the acceptors) and ``D = f+1`` delegate seats; seat ``d`` of
group ``g`` is served by server ``(d + seat_epoch[g]) mod S`` — a dead
server triggers a leader change that bumps the round AND the seating
rotation. Per-seat slot rings are ``[G, D, W]`` (owned ordinals; global
slot = ordinal * D + seat, the mencius-style stripe formula inside the
group); acceptor vote state is ``[A, G, D, W]`` with per-group promised
rounds. Phase-1 repair re-proposes in-flight slots with their original
values in the new round (full-information repair — the batched
convention also used by the flagship's oracle leader_change; the
matchmaker path there shows the true-quorum variant). The choose-once
ledger guards value stability across leader changes.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_ROUND,
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_delivered,
    bit_latency,
    ring_retire,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

EMPTY = 0
PROPOSED = 1
CHOSEN = 2

# Group phase.
PH_NORMAL = 0
PH_P1 = 1  # leader change: phase 1 in flight

NO_VALUE = -1
NOOP_VALUE = -2


@dataclasses.dataclass(frozen=True)
class BatchedFasterPaxosConfig:
    f: int = 1
    num_groups: int = 8  # G
    window: int = 16  # W: in-flight owned ordinals per seat
    slots_per_tick: int = 2  # K: proposals per live seat per tick
    lat_min: int = 1
    lat_max: int = 3
    drop_rate: float = 0.0
    retry_timeout: int = 16
    fail_rate: float = 0.0  # per-server per-tick death probability
    revive_rate: float = 0.05
    detect_timeout: int = 6  # ticks a seat is dead before leader change
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + a server-axis partition on the Phase2a plane
    # (UDP semantics); crash/revive merges into the native server churn
    # that drives dead-seat leader changes. FaultPlan.none() is a
    # structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-SEAT
    # admission (lane axis = the G x D delegate seats); noop fills stay
    # protocol traffic. WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def num_servers(self) -> int:
        return 2 * self.f + 1  # S (also the acceptor count A)

    @property
    def num_delegates(self) -> int:
        return self.f + 1  # D seats

    def __post_init__(self):
        assert self.f >= 1
        assert self.window >= 2 * self.slots_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.drop_rate < 1.0
        assert 0.0 <= self.fail_rate < 1.0
        assert 0.0 <= self.revive_rate <= 1.0
        assert self.detect_timeout >= 1
        self.faults.validate(axis=self.num_servers)
        self.workload.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedFasterPaxosState:
    """Shapes: [G] groups, [G, D, W] per-seat rings, [A, G, D, W]
    acceptor votes, [S, G] server liveness."""

    round: jnp.ndarray  # [G] current round
    seat_epoch: jnp.ndarray  # [G] delegate seating rotation
    phase: jnp.ndarray  # [G] PH_*
    dead_ticks: jnp.ndarray  # [G] consecutive ticks with a dead seat
    leader_changes: jnp.ndarray  # []

    next_ord: jnp.ndarray  # [G, D] next owned ordinal per seat
    head: jnp.ndarray  # [G, D] lowest non-retired owned ordinal

    status: jnp.ndarray  # [G, D, W]
    slot_value: jnp.ndarray  # [G, D, W]
    propose_tick: jnp.ndarray  # [G, D, W]
    last_send: jnp.ndarray  # [G, D, W]
    replica_arrival: jnp.ndarray  # [G, D, W]
    chosen_value: jnp.ndarray  # [G, D, W] choose-once ledger

    acc_round: jnp.ndarray  # [A, G] per-group promised round
    vote_round: jnp.ndarray  # [A, G, D, W] (-1 = none)
    p2a_arrival: jnp.ndarray  # [A, G, D, W]
    p2a_round: jnp.ndarray  # [A, G, D, W] round the Phase2a carries
    p2b_arrival: jnp.ndarray  # [A, G, D, W]

    server_alive: jnp.ndarray  # [S, G]
    p1a_arrival: jnp.ndarray  # [A, G] leader-change Phase1a
    p1b_arrival: jnp.ndarray  # [A, G]

    committed: jnp.ndarray  # []
    committed_real: jnp.ndarray  # []
    group_wm: jnp.ndarray  # [G] per-group execution watermark (monotone)
    noop_fills: jnp.ndarray  # [] stalled slots noop-filled at recovery
    deaths: jnp.ndarray  # []
    choose_violations: jnp.ndarray  # []
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedFasterPaxosConfig) -> BatchedFasterPaxosState:
    G, D, W = cfg.num_groups, cfg.num_delegates, cfg.window
    A = S = cfg.num_servers
    return BatchedFasterPaxosState(
        round=jnp.zeros((G,), DTYPE_ROUND),
        seat_epoch=jnp.zeros((G,), DTYPE_ROUND),
        phase=jnp.zeros((G,), DTYPE_STATUS),
        dead_ticks=jnp.zeros((G,), jnp.int32),
        leader_changes=jnp.zeros((), jnp.int32),
        next_ord=jnp.zeros((G, D), jnp.int32),
        head=jnp.zeros((G, D), jnp.int32),
        status=jnp.zeros((G, D, W), DTYPE_STATUS),
        slot_value=jnp.full((G, D, W), NO_VALUE, jnp.int32),
        propose_tick=jnp.full((G, D, W), INF, jnp.int32),
        last_send=jnp.full((G, D, W), INF, jnp.int32),
        replica_arrival=jnp.full((G, D, W), INF, jnp.int32),
        chosen_value=jnp.full((G, D, W), NO_VALUE, jnp.int32),
        acc_round=jnp.zeros((A, G), DTYPE_ROUND),
        vote_round=jnp.full((A, G, D, W), -1, DTYPE_ROUND),
        p2a_arrival=jnp.full((A, G, D, W), INF, jnp.int32),
        p2a_round=jnp.zeros((A, G, D, W), DTYPE_ROUND),
        p2b_arrival=jnp.full((A, G, D, W), INF, jnp.int32),
        server_alive=jnp.ones((S, G), bool),
        p1a_arrival=jnp.full((A, G), INF, jnp.int32),
        p1b_arrival=jnp.full((A, G), INF, jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        committed_real=jnp.zeros((), jnp.int32),
        group_wm=jnp.zeros((G,), jnp.int32),
        noop_fills=jnp.zeros((), jnp.int32),
        deaths=jnp.zeros((), jnp.int32),
        choose_violations=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_groups * cfg.num_delegates, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def _seat_server(cfg, seat_epoch):
    """[G, D] server index serving each delegate seat."""
    D, S = cfg.num_delegates, cfg.num_servers
    d_iota = jnp.arange(D, dtype=jnp.int32)[None, :]
    return jnp.mod(d_iota + seat_epoch[:, None], S)


def tick(
    cfg: BatchedFasterPaxosConfig,
    state: BatchedFasterPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedFasterPaxosState:
    G, D, W = cfg.num_groups, cfg.num_delegates, cfg.window
    A = S = cfg.num_servers
    f = cfg.f
    w_iota = jnp.arange(W, dtype=jnp.int32)
    d_iota = jnp.arange(D, dtype=jnp.int32)

    k4, k2, k1, kg = jax.random.split(key, 4)
    bits4 = jax.random.bits(k4, (A, G, D, W))  # [0:8) fwd, [8:16) bwd,
    #                                  [16:24) retry, [24:32) drop
    bits2 = jax.random.bits(k2, (G, D, W))  # [0:8) replica lat
    bits1 = jax.random.bits(k1, (S, G))  # [0:8) fail, [8:16) revive
    bitsg = jax.random.bits(kg, (A, G))  # [0:8) p1a, [8:16) p1b lat
    fwd_lat = bit_latency(bits4, 0, cfg.lat_min, cfg.lat_max)
    bwd_lat = bit_latency(bits4, 8, cfg.lat_min, cfg.lat_max)
    retry_lat = bit_latency(bits4, 16, cfg.lat_min, cfg.lat_max)
    rep_lat = bit_latency(bits2, 0, cfg.lat_min, cfg.lat_max)
    p1a_lat = bit_latency(bitsg, 0, cfg.lat_min, cfg.lat_max)
    p1b_lat = bit_latency(bitsg, 8, cfg.lat_min, cfg.lat_max)
    delivered = bit_delivered(bits4, 24, cfg.drop_rate)

    # Unified fault injection (tpu/faults.py): the plan folds into the
    # shared Phase2a delivered plane (partition cuts the server axis);
    # crash merges into the native churn below. none() skips all of it.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, A)[:, None, None, None]
        f_del, fwd_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (A, G, D, W), fwd_lat, link_up,
            rates=frates,
        )
        delivered = delivered & f_del

    status = state.status
    chosen_value = state.chosen_value

    # ---- 0. Server liveness churn (a FaultPlan crash schedule composes
    # with the native rates).
    eff_fail, eff_revive = faults_mod.effective_process_rates(
        fp, cfg.fail_rate, cfg.revive_rate, rates=frates
    )
    die = state.server_alive & ~bit_delivered(bits1, 0, eff_fail)
    revive = ~state.server_alive & ~bit_delivered(bits1, 8, eff_revive)
    server_alive = (state.server_alive & ~die) | revive
    deaths = state.deaths + jnp.sum(die)

    # ---- 1. Acceptors vote on Phase2as carrying a round >= their
    # group promise (stale-round stragglers from before a leader change
    # are rejected — Server.scala's round checks).
    p2a_now = state.p2a_arrival == t
    may_vote = p2a_now & (
        state.p2a_round >= state.acc_round[:, :, None, None]
    )
    vote_round = jnp.where(may_vote, state.p2a_round, state.vote_round)
    p2b_arrival = jnp.where(may_vote, t + bwd_lat, state.p2b_arrival)
    p2a_arrival = jnp.where(p2a_now, INF, state.p2a_arrival)

    # ---- 2. Choose: f+1 current-round Phase2bs.
    n_votes = jnp.sum(
        (p2b_arrival <= t)
        & (vote_round == state.round[None, :, None, None]),
        axis=0,
    )
    newly_chosen = (
        (status == PROPOSED)
        & (state.phase == PH_NORMAL)[:, None, None]
        & (n_votes >= f + 1)
    )
    choose_violations = state.choose_violations + jnp.sum(
        newly_chosen
        & (chosen_value != NO_VALUE)
        & (chosen_value != state.slot_value)
    )
    chosen_value = jnp.where(
        newly_chosen & (chosen_value == NO_VALUE),
        state.slot_value,
        chosen_value,
    )
    status = jnp.where(newly_chosen, CHOSEN, status)
    replica_arrival = jnp.where(
        newly_chosen, t + rep_lat, state.replica_arrival
    )
    real_chosen = newly_chosen & (state.slot_value != NOOP_VALUE)
    latency = jnp.where(real_chosen, t - state.propose_tick, 0)
    committed = state.committed + jnp.sum(newly_chosen)
    committed_real = state.committed_real + jnp.sum(real_chosen)
    lat_sum = state.lat_sum + jnp.sum(latency)
    bins = jnp.clip(latency, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        real_chosen.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )

    # ---- 3. Per-group execution watermark (min over seats of the
    # stripe formula) + retire.
    pos_of_ord = jnp.mod(state.head[:, :, None] + w_iota[None, None, :], W)
    ord_of_pos = state.head[:, :, None] + w_iota[None, None, :]
    chosen_ord = (
        jnp.take_along_axis(status, pos_of_ord, axis=2) == CHOSEN
    ) & (ord_of_pos < state.next_ord[:, :, None])
    n_contig = jnp.sum(
        jnp.cumprod(chosen_ord.astype(jnp.int32), axis=2), axis=2
    )  # [G, D]
    prefix = state.head + n_contig
    group_wm = jnp.min(prefix * D + d_iota[None, :], axis=1)  # [G]
    arrival_ord = jnp.take_along_axis(replica_arrival, pos_of_ord, axis=2)
    global_of_ord = ord_of_pos * D + d_iota[None, :, None]
    retire_ord = (
        chosen_ord
        & (arrival_ord <= t)
        & (global_of_ord < group_wm[:, None, None])
    )
    GD = G * D
    n_retire, retire_mask = ring_retire(
        retire_ord.reshape(GD, W), state.head.reshape(GD)
    )
    head = state.head + n_retire.reshape(G, D)
    retire_mask = retire_mask.reshape(G, D, W)

    status = jnp.where(retire_mask, EMPTY, status)
    slot_value = jnp.where(retire_mask, NO_VALUE, state.slot_value)
    chosen_value = jnp.where(retire_mask, NO_VALUE, chosen_value)
    propose_tick = jnp.where(retire_mask, INF, state.propose_tick)
    last_send = jnp.where(retire_mask, INF, state.last_send)
    replica_arrival = jnp.where(retire_mask, INF, replica_arrival)
    clear4 = retire_mask[None, :, :, :]
    vote_round = jnp.where(clear4, -1, vote_round)
    p2a_arrival = jnp.where(clear4, INF, p2a_arrival)
    p2b_arrival = jnp.where(clear4, INF, p2b_arrival)

    # ---- 4. Dead-seat detection -> leader change (Server.scala:
    # 497-530 leaderChangeTimer): when a seat's server has been dead for
    # detect_timeout ticks, bump the round, start phase 1, and rotate
    # the seating until every seat lands on a live server.
    seat_server = _seat_server(cfg, state.seat_epoch)  # [G, D]
    seat_alive = jnp.take_along_axis(
        server_alive.T, seat_server, axis=1
    )  # [G, D]
    any_dead = ~jnp.all(seat_alive, axis=1)  # [G]
    dead_ticks = jnp.where(
        any_dead & (state.phase == PH_NORMAL), state.dead_ticks + 1, 0
    )
    start_lc = dead_ticks >= cfg.detect_timeout
    # New seating: try successive rotations; pick the first (cyclic)
    # rotation whose seats are all alive. With S = 2f+1 servers, D = f+1
    # seats and at most f dead, some rotation works; if none (transient
    # mass failure), keep rotating next time.
    def seating_ok(epoch):
        srv = jnp.mod(
            d_iota[None, :] + epoch[:, None], S
        )
        return jnp.all(
            jnp.take_along_axis(server_alive.T, srv, axis=1), axis=1
        )

    new_epoch = state.seat_epoch
    chosen_rotation = jnp.zeros((G,), bool)
    for shift in range(1, S + 1):
        cand = state.seat_epoch + shift
        ok = seating_ok(cand) & ~chosen_rotation
        new_epoch = jnp.where(ok, cand, new_epoch)
        chosen_rotation = chosen_rotation | ok
    seat_epoch = jnp.where(start_lc, new_epoch, state.seat_epoch)
    round_ = jnp.where(start_lc, state.round + 1, state.round)
    phase = jnp.where(start_lc, PH_P1, state.phase)
    leader_changes = state.leader_changes + jnp.sum(start_lc)
    dead_ticks = jnp.where(start_lc, 0, dead_ticks)
    p1a_arrival = jnp.where(
        start_lc[None, :], t + p1a_lat, state.p1a_arrival
    )

    # ---- 5. Phase 1: acceptors promise the new round; f+1 Phase1bs
    # complete it. Repair is full-information (see module docstring):
    # every in-flight PROPOSED slot is re-proposed with its ORIGINAL
    # value in the new round; owned-but-never-proposed stalled slots of
    # the OLD seating below the group's allocation frontier are
    # noop-filled (the Recover path for holes).
    p1a_now = state.p1a_arrival == t
    acc_round = jnp.maximum(
        state.acc_round, jnp.where(p1a_now, round_[None, :], 0)
    )
    p1b_arrival = jnp.where(p1a_now, t + p1b_lat, state.p1b_arrival)
    p1a_arrival = jnp.where(p1a_now, INF, p1a_arrival)
    p1_done = (state.phase == PH_P1) & (
        jnp.sum(p1b_arrival <= t, axis=0) >= f + 1
    )
    phase = jnp.where(p1_done, PH_NORMAL, phase)
    p1b_arrival = jnp.where(p1_done[None, :], INF, p1b_arrival)
    # Repair: re-send Phase2as (new round) for in-flight slots.
    repair = p1_done[:, None, None] & (status == PROPOSED)
    # Noop-fill holes: seats whose next_ord lags the group's max seat
    # frontier get their missing ordinals allocated as noops (below the
    # frontier nothing new will arrive for them — they stall the
    # watermark otherwise).
    max_ord = jnp.max(state.next_ord, axis=1)  # [G]
    lag = jnp.maximum(max_ord[:, None] - state.next_ord, 0)  # [G, D]
    space = W - (state.next_ord - head)
    fill = jnp.where(
        p1_done[:, None], jnp.minimum(lag, space), 0
    )  # [G, D]
    delta = jnp.mod(
        w_iota[None, None, :] - state.next_ord[:, :, None], W
    )
    is_fill = delta < fill[:, :, None]
    next_ord = state.next_ord + fill
    noop_fills = state.noop_fills + jnp.sum(fill)
    status = jnp.where(is_fill, PROPOSED, status)
    slot_value = jnp.where(is_fill, NOOP_VALUE, slot_value)
    propose_tick = jnp.where(is_fill, t, propose_tick)
    send_now = repair | is_fill
    last_send = jnp.where(send_now, t, last_send)
    p2a_arrival = jnp.where(
        send_now[None, :, :, :] & delivered, t + fwd_lat, p2a_arrival
    )
    p2a_round = jnp.where(
        send_now[None, :, :, :],
        round_[None, :, None, None],
        state.p2a_round,
    )

    # ---- 6. Delegate proposals (PH_NORMAL, live seats): K owned
    # ordinals per seat per tick, proposed directly in the current round
    # (the Phase2aAny grant — no leader hop).
    seat_server2 = _seat_server(cfg, seat_epoch)
    seat_alive2 = jnp.take_along_axis(server_alive.T, seat_server2, axis=1)
    space2 = W - (next_ord - head)
    can = (
        (phase == PH_NORMAL)[:, None] & seat_alive2
    )
    # Workload admission (tpu/workload.py): the lane axis is the G x D
    # delegate seats; under a shaping plan the static knob becomes the
    # per-seat admission cap.
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, G * D)
        adm = workload_mod.admission(wl, wls, wl_writes).reshape(G, D)
        count = jnp.where(can, jnp.minimum(adm, space2), 0)
    else:
        count = jnp.where(
            can, jnp.minimum(cfg.slots_per_tick, space2), 0
        )
    delta2 = jnp.mod(w_iota[None, None, :] - next_ord[:, :, None], W)
    is_new = delta2 < count[:, :, None]
    new_ord = next_ord[:, :, None] + delta2
    g_ids = jnp.arange(G, dtype=jnp.int32)[:, None, None]
    new_val = (
        (new_ord * D + d_iota[None, :, None]) * G + g_ids
    ) & jnp.int32(0x7FFFFFFF)
    next_ord = next_ord + count
    if wl.active:
        # Completions: an admitted (real-valued) slot resolves at its
        # choose, even when a repair chose a noop over it.
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count.reshape(G * D),
            jnp.sum(
                newly_chosen & (state.slot_value != NOOP_VALUE), axis=2
            ).reshape(G * D),
        )
    status = jnp.where(is_new, PROPOSED, status)
    slot_value = jnp.where(is_new, new_val, slot_value)
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    p2a_arrival = jnp.where(
        is_new[None, :, :, :] & delivered, t + fwd_lat, p2a_arrival
    )
    p2a_round = jnp.where(
        is_new[None, :, :, :], round_[None, :, None, None], p2a_round
    )

    # ---- 7. Retries (live seats, normal phase).
    timed_out = (
        (status == PROPOSED)
        & (phase == PH_NORMAL)[:, None, None]
        & seat_alive2[:, :, None]
        & (t - last_send >= cfg.retry_timeout)
    )
    p2a_arrival = jnp.where(
        timed_out[None, :, :, :], t + retry_lat, p2a_arrival
    )
    p2a_round = jnp.where(
        timed_out[None, :, :, :], round_[None, :, None, None], p2a_round
    )
    last_send = jnp.where(timed_out, t, last_send)

    new_group_wm = jnp.maximum(state.group_wm, group_wm)
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase1_msgs=A * (leader_changes - state.leader_changes),
        phase2_msgs=jnp.sum(is_new[None, :, :, :] & delivered)
        + A * jnp.sum(timed_out),
        commits=committed - state.committed,
        executes=jnp.sum(new_group_wm - state.group_wm),
        drops=jnp.sum(is_new[None, :, :, :] & ~delivered),
        retries=jnp.sum(timed_out),
        leader_changes=leader_changes - state.leader_changes,
        queue_depth=jnp.sum(next_ord - head),
        queue_capacity=G * D * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    return BatchedFasterPaxosState(
        round=round_,
        seat_epoch=seat_epoch,
        phase=phase,
        dead_ticks=dead_ticks,
        leader_changes=leader_changes,
        next_ord=next_ord,
        head=head,
        status=status,
        slot_value=slot_value,
        propose_tick=propose_tick,
        last_send=last_send,
        replica_arrival=replica_arrival,
        chosen_value=chosen_value,
        acc_round=acc_round,
        vote_round=vote_round,
        p2a_arrival=p2a_arrival,
        p2a_round=p2a_round,
        p2b_arrival=p2b_arrival,
        server_alive=server_alive,
        p1a_arrival=p1a_arrival,
        p1b_arrival=p1b_arrival,
        committed=committed,
        committed_real=committed_real,
        group_wm=new_group_wm,
        noop_fills=noop_fills,
        deaths=deaths,
        choose_violations=choose_violations,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedFasterPaxosConfig,
    state: BatchedFasterPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedFasterPaxosState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedFasterPaxosConfig, state: BatchedFasterPaxosState, t
) -> dict:
    # THE delegate-repartitioning safety property: a chosen slot's value
    # never changes across leader changes.
    choose_once = state.choose_violations == 0
    window_ok = jnp.all(
        (state.head <= state.next_ord)
        & (state.next_ord - state.head <= cfg.window)
    )
    # Acceptor promises never fall behind the group round the leader
    # reached phase-2 in... (promises are bumped by phase 1; during PH_P1
    # some acceptors may still lag).
    round_ok = jnp.all(
        jnp.where(
            state.phase == PH_NORMAL,
            jnp.max(state.acc_round, axis=0) >= state.round,
            True,
        )
    )
    # Votes only in rounds the group actually ran.
    vote_ok = jnp.all(state.vote_round <= state.round[None, :, None, None])
    books_ok = state.committed_real <= state.committed
    return {
        "choose_once": choose_once,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "window_ok": window_ok,
        "round_ok": round_ok,
        "vote_ok": vote_ok,
        "books_ok": books_ok,
    }


def stats(
    cfg: BatchedFasterPaxosConfig, state: BatchedFasterPaxosState, t
) -> dict:
    real = int(state.committed_real)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (real + 1) // 2)).argmax())
        if real
        else -1
    )
    return {
        "ticks": int(t),
        "committed": int(state.committed),
        "committed_real": real,
        "executed_global": int(jax.device_get(state.group_wm).sum()),
        "leader_changes": int(state.leader_changes),
        "noop_fills": int(state.noop_fills),
        "deaths": int(state.deaths),
        "choose_violations": int(state.choose_violations),
        "latency_p50_ticks": p50,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedFasterPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedFasterPaxosConfig(
        num_groups=4, window=8, slots_per_tick=2, workload=workload,
        retry_timeout=8, faults=faults,
    )
