"""Batched Mencius as a single XLA program (the reference's second
headline protocol: compartmentalized Mencius, 803,881 cmd/s in
BASELINE.md).

Mencius stripes one GLOBAL log round-robin across ``L`` leaders: leader
``l`` owns slots ``{q*L + l}`` (``mencius/``, ``vanillamencius/``). Three
mechanisms distinguish it from the batched MultiPaxos model:

  * **Heterogeneous load**: any leader may be idle in a tick (Bernoulli
    ``idle_rate``), so stripes advance at different speeds.
  * **Skips**: a leader that falls behind the fastest stripe by more
    than ``skip_threshold`` noop-fills its owned slots up to the
    broadcast high watermark (``MenciusHighWatermark`` /
    ``Leader.scala`` skip logic) — modeled as noop proposals through the
    normal quorum path.
  * **Global execution watermark**: replicas execute the longest
    contiguous GLOBAL prefix. With per-stripe contiguous commit prefixes
    ``c_l`` (slots ``l, l+L, ..., l+(c_l-1)L``), the global prefix
    length is ``min over l of (c_l * L + l)`` — a single min-reduction
    across the leader axis (the cross-shard collective when leaders are
    sharded over a device mesh; SURVEY §2.7 "log partitioning ->
    static index maps; cut prefix-sums").

Everything else (votes, quorums, ring windows, retry, PRNG bit-field
sampling) reuses the batched MultiPaxos machinery's design.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_delivered,
    bit_latency,
    ring_retire,
)
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

EMPTY = 0
PROPOSED = 1
CHOSEN = 2

NO_VALUE = -1
NOOP_VALUE = -2  # a skip (Leader.scala noop range fill)


@dataclasses.dataclass(frozen=True)
class BatchedMenciusConfig:
    """Static simulation parameters. Each leader stripe has its own
    2f+1-acceptor group (colocated deployment)."""

    f: int = 1
    num_leaders: int = 4  # L: stripes of the global log
    window: int = 32  # W: in-flight owned slots per leader
    slots_per_tick: int = 4  # K: proposals per ACTIVE leader per tick
    idle_rate: float = 0.0  # P(a leader proposes nothing this tick)
    # Leaders 0..num_idle_leaders-1 carry NO client load at all (an
    # unloaded or partitioned stripe) — without skips they pin the
    # global watermark at zero.
    num_idle_leaders: int = 0
    skip_threshold: int = 16  # lag (in owned slots) that triggers skips
    lat_min: int = 1
    lat_max: int = 3
    drop_rate: float = 0.0
    retry_timeout: int = 16
    max_slots_per_leader: Optional[int] = None
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + an acceptor-axis partition on the Phase2a/
    # Phase2b/retry planes (UDP semantics — retries restore liveness
    # after a heal); crash/revive stops a dead leader's stripe (skips
    # catch it up after revival). FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes each ACTIVE
    # leader's per-tick proposal admission (skip fills are protocol
    # noops, not workload entries). WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Kernel-layer dispatch policy (ops/registry.py): the per-slot
    # vote/skip aggregation plane (tick steps 1-2) routes through
    # ops.registry.dispatch — fused Pallas on TPU, pure-jnp reference
    # elsewhere under the default "auto" mode.
    kernels: KernelPolicy = KernelPolicy()

    @property
    def group_size(self) -> int:
        return 2 * self.f + 1

    def __post_init__(self):
        assert self.f >= 1
        assert self.num_leaders >= 2
        assert self.window >= 2 * self.slots_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.drop_rate < 1.0
        assert 0.0 <= self.idle_rate < 1.0
        assert 0 <= self.num_idle_leaders < self.num_leaders
        assert self.skip_threshold >= 1
        self.faults.validate(axis=self.group_size)
        self.workload.validate()
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedMenciusState:
    """Shapes: [L] leaders, [L, W] owned-slot rings, [L, W, A] votes."""

    next_slot: jnp.ndarray  # [L] next OWNED slot ordinal (global = o*L + l)
    head: jnp.ndarray  # [L] lowest non-retired owned ordinal

    status: jnp.ndarray  # [L, W]
    slot_value: jnp.ndarray  # [L, W] value id or NOOP_VALUE for skips
    propose_tick: jnp.ndarray  # [L, W]
    last_send: jnp.ndarray  # [L, W]
    chosen_tick: jnp.ndarray  # [L, W]
    replica_arrival: jnp.ndarray  # [L, W]
    committed_prefix: jnp.ndarray  # [L] contiguous committed owned ordinals

    p2a_arrival: jnp.ndarray  # [L, W, A]
    p2b_arrival: jnp.ndarray  # [L, W, A]
    voted: jnp.ndarray  # [L, W, A] bool

    # Leader liveness under a FaultPlan crash schedule (all-True and
    # untouched otherwise); a dead leader's stripe stalls the global
    # watermark until revival, then skips catch it up.
    fault_alive: jnp.ndarray  # [L] bool

    executed_global: jnp.ndarray  # [] global contiguous prefix length
    committed: jnp.ndarray  # [] cumulative chosen slots (incl. skips)
    committed_real: jnp.ndarray  # [] cumulative chosen REAL commands
    skips: jnp.ndarray  # [] cumulative noop skip proposals
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedMenciusConfig) -> BatchedMenciusState:
    L, W, A = cfg.num_leaders, cfg.window, cfg.group_size
    return BatchedMenciusState(
        next_slot=jnp.zeros((L,), jnp.int32),
        head=jnp.zeros((L,), jnp.int32),
        status=jnp.zeros((L, W), DTYPE_STATUS),
        slot_value=jnp.full((L, W), NO_VALUE, jnp.int32),
        propose_tick=jnp.full((L, W), INF, jnp.int32),
        last_send=jnp.full((L, W), INF, jnp.int32),
        chosen_tick=jnp.full((L, W), INF, jnp.int32),
        replica_arrival=jnp.full((L, W), INF, jnp.int32),
        committed_prefix=jnp.zeros((L,), jnp.int32),
        p2a_arrival=jnp.full((L, W, A), INF, jnp.int32),
        p2b_arrival=jnp.full((L, W, A), INF, jnp.int32),
        voted=jnp.zeros((L, W, A), bool),
        fault_alive=jnp.ones((L,), bool),
        executed_global=jnp.zeros((), jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        committed_real=jnp.zeros((), jnp.int32),
        skips=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(cfg.workload, L, cfg.faults),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedMenciusConfig,
    state: BatchedMenciusState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedMenciusState:
    """One tick: acceptors vote, quorums form, the global prefix
    advances, active leaders propose, lagging leaders skip-fill."""
    L, W, A = cfg.num_leaders, cfg.window, cfg.group_size
    f = cfg.f
    k3, k2, k_extra = jax.random.split(key, 3)
    bits3 = jax.random.bits(k3, (L, W, A))
    bits2 = jax.random.bits(k2, (L, W))
    bits1 = jax.random.bits(jax.random.fold_in(k_extra, 2), (L,))
    p2b_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    p2a_lat = bit_latency(bits3, 8, cfg.lat_min, cfg.lat_max)
    retry_lat = bit_latency(bits3, 16, cfg.lat_min, cfg.lat_max)
    rep_lat = bit_latency(bits2, 0, cfg.lat_min, cfg.lat_max)
    p2b_delivered = bit_delivered(bits3, 24, cfg.drop_rate)
    if cfg.drop_rate > 0.0:
        p2a_delivered = bit_delivered(
            jax.random.bits(jax.random.fold_in(k_extra, 0), (L, W, A)),
            0,
            cfg.drop_rate,
        )
    else:
        p2a_delivered = jnp.ones((L, W, A), bool)

    # Unified fault injection (tpu/faults.py): UDP semantics on the
    # Phase2a/Phase2b/retry planes; partition cuts acceptor links
    # (minor axis), crash stops a leader's stripe. none() is skipped at
    # trace time entirely.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    retry_delivered = None
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, A)[None, None, :]
        f_del, p2a_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (L, W, A), p2a_lat, link_up,
            rates=frates,
        )
        p2a_delivered = p2a_delivered & f_del
        f_del, p2b_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (L, W, A), p2b_lat, link_up,
            rates=frates,
        )
        p2b_delivered = p2b_delivered & f_del
        retry_delivered, retry_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 2), (L, W, A), retry_lat, link_up,
            rates=frates,
        )
    fault_alive = state.fault_alive
    if fp.has_crash:
        fault_alive = faults_mod.crash_step(
            fp, faults_mod.fault_key(key, 9), fault_alive, rates=frates
        )

    status = state.status
    w_iota = jnp.arange(W, dtype=jnp.int32)

    # ---- 1+2. Acceptors vote on Phase2a arrivals (no competing rounds
    # in the steady-state Mencius write path: each leader owns its
    # stripe), Phase2b replies schedule, and the per-slot quorum count
    # sums the acceptor axis — one registry plane (ops/mencius.py):
    # fused VMEM-resident Pallas on TPU, the pure-jnp reference (the
    # exact program this tick ran before the fusion) elsewhere.
    voted, p2b_arrival, nvotes = ops_registry.dispatch(
        "mencius_vote",
        cfg,
        state.p2a_arrival,
        state.voted,
        state.p2b_arrival,
        p2b_lat,
        p2b_delivered,
        t,
    )
    newly_chosen = (status == PROPOSED) & (nvotes >= f + 1)
    # Span sampler input, captured BEFORE retirement wipes the vote
    # plane: mencius runs on ABSOLUTE message clocks, so a vote is
    # visible exactly when the quorum counter sees it (arrival <= t).
    span_voted = jnp.any(voted & (p2b_arrival <= t), axis=2)
    chosen_tick = jnp.where(newly_chosen, t, state.chosen_tick)
    replica_arrival = jnp.where(newly_chosen, t + rep_lat, state.replica_arrival)
    status = jnp.where(newly_chosen, CHOSEN, status)

    # Latency/throughput stats count REAL commands only: noop skip fills
    # flow through the same quorum path (they are chosen slots), but they
    # carry no client command, so mixing them in would inflate the
    # headline committed rate and dilute the latency distribution on
    # idle-skewed runs. ``committed`` counts all chosen slots (incl.
    # skips, tracked separately in ``skips``); ``committed_real`` and the
    # histogram cover commands only.
    real_chosen = newly_chosen & (state.slot_value != NOOP_VALUE)
    latency = jnp.where(real_chosen, t - state.propose_tick, 0)
    committed = state.committed + jnp.sum(newly_chosen)
    committed_real = state.committed_real + jnp.sum(real_chosen)
    lat_sum = state.lat_sum + jnp.sum(latency)
    bins = jnp.clip(latency, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        real_chosen.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )

    # ---- 3. Per-stripe contiguous commit prefix, then the GLOBAL
    # execution watermark: executed_global = min_l (c_l * L + l). Retire
    # owned slots whose Chosen reached the replicas AND whose global slot
    # is below the watermark.
    slot_of_ord = state.head[:, None] + w_iota[None, :]
    pos_of_ord = slot_of_ord % W
    chosen_ord = (
        (jnp.take_along_axis(status, pos_of_ord, axis=1) == CHOSEN)
        & (slot_of_ord < state.next_slot[:, None])
    )
    # c_l: committed prefix in owned ordinals (head-based contiguity).
    n_contig = jnp.sum(jnp.cumprod(chosen_ord.astype(jnp.int32), axis=1), axis=1)
    committed_prefix = state.head + n_contig  # [L] owned ordinals
    stripe_ids = jnp.arange(L, dtype=jnp.int32)
    executed_global = jnp.min(committed_prefix * L + stripe_ids)

    # Retire: chosen, replica-visible, and globally executable.
    arrival_ord = jnp.take_along_axis(replica_arrival, pos_of_ord, axis=1)
    global_of_ord = slot_of_ord * L + stripe_ids[:, None]
    retire_ord = (
        chosen_ord & (arrival_ord <= t) & (global_of_ord < executed_global)
    )
    n_retire, retire_mask = ring_retire(retire_ord, state.head)
    head = state.head + n_retire

    status = jnp.where(retire_mask, EMPTY, status)
    slot_value = jnp.where(retire_mask, NO_VALUE, state.slot_value)
    chosen_tick = jnp.where(retire_mask, INF, chosen_tick)
    replica_arrival = jnp.where(retire_mask, INF, replica_arrival)
    propose_tick = jnp.where(retire_mask, INF, state.propose_tick)
    last_send = jnp.where(retire_mask, INF, state.last_send)
    p2a_arrival = jnp.where(retire_mask[:, :, None], INF, state.p2a_arrival)
    p2b_arrival = jnp.where(retire_mask[:, :, None], INF, p2b_arrival)
    voted = jnp.where(retire_mask[:, :, None], False, voted)

    # ---- 4. Proposals. An idle leader proposes nothing; a LAGGING
    # leader (more than skip_threshold owned slots behind the fastest
    # stripe) noop-fills its backlog this tick (the high-watermark skip,
    # Leader.scala _skip_to) — skips flow through the normal quorum path.
    # Reuse the guarded 8-bit Bernoulli (a tiny nonzero idle_rate must
    # not quantize to never-idle).
    idle = ~bit_delivered(bits1, 0, cfg.idle_rate)
    if cfg.num_idle_leaders:
        idle = idle | (jnp.arange(L) < cfg.num_idle_leaders)
    if fp.has_crash:
        # A crashed leader neither proposes nor skips (skipping is the
        # LIVE laggard's mechanism); its stripe pins the global
        # watermark until revival — plain Mencius has no revocation
        # (that is vanillamencius's mechanic).
        idle = idle | ~fault_alive
    max_next = jnp.max(state.next_slot)
    lag = max_next - state.next_slot  # [L] owned-slot lag
    skipping = lag > cfg.skip_threshold
    if fp.has_crash:
        skipping = skipping & fault_alive

    space = W - (state.next_slot - head)
    # Workload admission (tpu/workload.py): under a shaping plan the
    # static slots_per_tick knob becomes the per-leader admission cap;
    # skip fills stay protocol noops outside the workload accounting.
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, L)
        adm = workload_mod.admission(wl, wls, wl_writes)
    else:
        adm = cfg.slots_per_tick
    want = jnp.where(
        skipping,
        jnp.minimum(lag, W),  # fill the backlog with noops
        jnp.where(idle, 0, adm),
    )
    count = jnp.minimum(want, space)
    if cfg.max_slots_per_leader is not None:
        count = jnp.minimum(
            count, jnp.maximum(cfg.max_slots_per_leader - state.next_slot, 0)
        )
    delta = (w_iota[None, :] - state.next_slot[:, None]) % W
    is_new = delta < count[:, None]
    next_slot = state.next_slot + count
    skips = state.skips + jnp.sum(jnp.where(skipping, count, 0))
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes,
            jnp.where(skipping, 0, count),
            jnp.sum(real_chosen, axis=1),
        )

    new_ord = state.next_slot[:, None] + delta
    new_value = jnp.where(
        skipping[:, None],
        NOOP_VALUE,
        (new_ord * L + stripe_ids[:, None]) & jnp.int32(0x7FFFFFFF),
    )
    status = jnp.where(is_new, PROPOSED, status)
    slot_value = jnp.where(is_new, new_value, slot_value)
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    p2a_arrival = jnp.where(
        is_new[:, :, None] & p2a_delivered, t + p2a_lat, p2a_arrival
    )

    # ---- 5. Retries.
    timed_out = (status == PROPOSED) & (t - last_send >= cfg.retry_timeout)
    resend = timed_out[:, :, None]
    if retry_delivered is not None:
        resend = resend & retry_delivered
    p2a_arrival = jnp.where(resend, t + retry_lat, p2a_arrival)
    last_send = jnp.where(timed_out, t, last_send)

    new_executed_global = jnp.maximum(state.executed_global, executed_global)
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase2_msgs=jnp.sum(is_new[:, :, None] & p2a_delivered)
        + A * jnp.sum(timed_out),
        commits=committed - state.committed,
        executes=new_executed_global - state.executed_global,
        drops=jnp.sum(is_new[:, :, None] & ~p2a_delivered),
        retries=jnp.sum(timed_out),
        queue_depth=jnp.sum(next_slot - head),
        queue_capacity=L * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    # Span sampler (telemetry.record_spans — the generic plumbing):
    # slot lifecycles in the striped log, recorded from the masks this
    # tick already computed. Mapping: group = leader stripe, slot id =
    # the owned ordinal at each ring position (OLD head — valid for
    # every cell occupied at tick start, including this tick's
    # retirees); a cell proposed THIS tick carries the OLD next_slot
    # ordinal (``new_ord`` — retire + re-propose in one tick crosses a
    # full window). No phase-1 plane in steady-state Mencius (each
    # leader owns its stripe, so there is nothing to promise).
    # Structurally OFF at spans=0, like the counter ring.
    if telemetry_mod.span_slots(tel):
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=is_new,
            slot_ids=state.head[:, None]
            + (w_iota[None, :] - state.head[:, None]) % W,
            new_slot_ids=new_ord,
            phase1_mark=jnp.zeros((L,), bool),
            voted=span_voted,
            newly_chosen=newly_chosen,
            retire_mask=retire_mask,
        )

    return BatchedMenciusState(
        next_slot=next_slot,
        head=head,
        status=status,
        slot_value=slot_value,
        propose_tick=propose_tick,
        last_send=last_send,
        chosen_tick=chosen_tick,
        replica_arrival=replica_arrival,
        committed_prefix=committed_prefix,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        voted=voted,
        fault_alive=fault_alive,
        executed_global=new_executed_global,
        committed=committed,
        committed_real=committed_real,
        skips=skips,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedMenciusConfig,
    state: BatchedMenciusState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedMenciusState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedMenciusConfig, state: BatchedMenciusState, t
) -> dict:
    """Device-side safety checks; all booleans must be True."""
    L = cfg.num_leaders
    stripe_ids = jnp.arange(L, dtype=jnp.int32)
    # The global watermark never exceeds the min-stripe formula.
    watermark_ok = state.executed_global <= jnp.min(
        state.committed_prefix * L + stripe_ids
    )
    # Window bookkeeping.
    window_ok = jnp.all(
        (state.head <= state.next_slot)
        & (state.next_slot - state.head <= cfg.window)
    )
    # Chosen slots have a quorum of votes.
    chosen = state.status == CHOSEN
    quorum_ok = jnp.all(
        jnp.where(
            chosen,
            jnp.sum(state.voted & (state.p2b_arrival <= t), axis=2)
            >= cfg.f + 1,
            True,
        )
    )
    # Retired slots were globally executable: heads never pass the
    # committed prefix.
    head_ok = jnp.all(state.head <= state.committed_prefix)
    return {
        "watermark_ok": watermark_ok,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "window_ok": window_ok,
        "quorum_ok": quorum_ok,
        "head_ok": head_ok,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedMenciusConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedMenciusConfig(
        f=1, num_leaders=4, window=16, slots_per_tick=2,
        workload=workload,
        retry_timeout=8, faults=faults,
    )
