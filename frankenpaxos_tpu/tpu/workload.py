"""In-graph workload engine: open/closed-loop traffic shaping for the
batched backends.

Every driver used to commit at whatever rate the tick sustained — pure
saturation throughput, no latency-vs-load story. The reference framework
treats workloads as first-class (``benchmarks/``: read/write mixes, key
skew, client think time), and the Compartmentalization report (arxiv
2012.15762) evaluates every design point as a latency-vs-throughput
curve under shaped load. This module is that vocabulary rebuilt
TPU-first, the traffic-shape twin of :mod:`frankenpaxos_tpu.tpu.faults`:
a single :class:`WorkloadPlan` accepted by EVERY ``tpu/*_batched.py``
config, applied INSIDE the compiled tick, so millions of simulated
clients are just a vmapped client axis and a whole [workload x fault]
grid sweeps under one compile.

Model: each backend exposes a LANE axis (its proposer axis — groups,
servers, leaders, columns ...). Per tick, the engine

  * draws per-lane request ARRIVALS from the plan's arrival process
    (``constant`` — a deterministic 16-bit fixed-point accumulator with
    exact long-run rate; ``poisson``; ``bursty`` — Poisson with a
    square-wave rate multiplier; ``diurnal`` — Poisson with a phase
    schedule of rate multipliers), skewed across lanes by a Zipfian
    weight vector (:func:`zipf_weights` — lane 0 is the hot key),
  * splits arrivals into writes and reads by ``read_fraction`` (a
    second fixed-point accumulator; reads feed the backend's read path
    where one exists),
  * queues writes in a bounded per-lane FIFO BACKLOG and computes the
    tick's per-lane ADMISSION CAP — the cap simply clamps the backend's
    existing proposals-per-tick knob, so admission composes ahead of
    the kernel planes with no kernel-plane signature changes,
  * models CLOSED-LOOP clients as an outstanding-request window per
    lane: ``closed_window`` clients each issue one request, wait for
    its commit, think for ``think_time`` ticks (a ring of expiry
    counts — the offset-clock encoding of think time), then re-issue.
    Admission is gated on completions: ``in_flight`` never exceeds the
    window, conserved exactly (``tests/test_workload.py``),
  * accounts per-entry queue WAIT exactly (arrival tick -> admission
    tick) into :data:`WAIT_BINS` histogram bins via the cumulative-
    arrival ring trick: FIFO admission means the entries admitted this
    tick with wait ``j`` are exactly the overlap of the admission index
    interval with the arrival-count interval of tick ``t - j`` — an
    O(lanes x WAIT_BINS) computation, no per-entry timestamps. The
    admission-tick -> commit-tick latency of every admitted entry lands
    in the existing telemetry/lat_hist bins (admission IS the propose
    tick), so the two histograms together are the client-visible
    latency decomposition.

The OFFERED RATE is a TRACED state-side scalar (``WorkloadState.rate``,
initialized from ``plan.rate``): sweeping the offered-load axis — the
whole latency-vs-load matrix — replays ONE compiled program with a
different scalar, and vmapping the scalar fans the grid out on-device.
:class:`WorkloadState` also carries the traced Bernoulli rates of a
``FaultPlan(traced=True)`` (:func:`frankenpaxos_tpu.tpu.faults
.make_rates`), so one compile sweeps a [workload x fault-rate] grid.

Determinism contract: all workload randomness derives from the tick's
own threefry key via ``fold_in`` with :data:`WORKLOAD_SALT` (disjoint
from the fault stream). ``WorkloadPlan.none()`` (the default on every
config) is a STRUCTURAL no-op: every :class:`WorkloadState` leaf is
zero-sized, every helper returns its inputs untouched at trace time,
no key is ever derived — XLA emits the exact pre-workload program and
runs stay bit-identical to the pre-PR goldens (pinned by
``tests/test_workload.py`` against the ``tests/test_faults.py`` golden
values; the ``trace-workload-noop`` analysis rule pins the structure).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import packing
from frankenpaxos_tpu.tpu.faults import FaultPlan

# Stream id folded into a tick's key before drawing any workload
# randomness. Distinct from faults.FAULT_SALT and every backend salt.
WORKLOAD_SALT = 0x10AD

# Queue-wait histogram bins (== the cumulative-arrival ring length):
# waits of WAIT_BINS-1 ticks and beyond saturate into the last bin.
WAIT_BINS = 32

# 16-bit fixed point for the deterministic arrival/read accumulators.
_FP_ONE = 65536

ARRIVALS = ("saturate", "constant", "poisson", "bursty", "diurnal", "trace")

_RATE_FIELDS = ("rate", "burst_mult", "zipf_s", "read_fraction")

# Backends with a device read path (a read ring the engine's read split
# can feed). The read-mix validation names these so a misconfigured run
# fails with the fix in the message, not just the symptom.
READ_BACKENDS = ("craq", "compartmentalized", "multipaxos")


def zipf_weights(n: int, s: float):
    """Zipfian lane weights, shared by the device plan and the host
    command-byte generators (``harness/workload.py``): rank ``i`` gets
    weight ``(i+1)^-s``, normalized to MEAN 1 over ``n`` lanes (so the
    plan's ``rate`` stays the per-lane mean regardless of skew). Lane 0
    is the hot key; ``s == 0`` is uniform."""
    import numpy as np

    assert n >= 1 and s >= 0.0
    w = (np.arange(1, n + 1, dtype=np.float64)) ** (-float(s))
    return (w * (n / w.sum())).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class WorkloadPlan:
    """One traffic shape. Frozen + hashable: lives inside the static
    backend config (a ``jax.jit`` static argument). The plan fixes the
    STRUCTURE (process kind, window, think time, skew); the offered
    rate itself is traced state (:class:`WorkloadState`), initialized
    from ``rate``, so rate sweeps never recompile."""

    # Arrival process over the lane axis. "saturate" = no shaping (the
    # pre-plan behavior: the backend proposes at its static per-tick
    # knob); the other four draw per-tick per-lane arrival counts.
    arrival: str = "saturate"
    rate: float = 0.0  # mean arrivals per lane per tick (traced default)
    # "bursty": rate multiplies by burst_mult for the first burst_len
    # ticks of every burst_every-tick period.
    burst_every: int = 64
    burst_len: int = 8
    burst_mult: float = 4.0
    # "diurnal": a phase schedule of rate multipliers — phase p covers
    # ticks [p*phase_len, (p+1)*phase_len) mod the full period.
    phases: Tuple[float, ...] = ()
    phase_len: int = 64
    # Zipfian skew of arrivals across the lane axis (0 = uniform; lane
    # 0 is the hot key). Static: the skew vector is a trace constant.
    zipf_s: float = 0.0
    # Fraction of arrivals that are READS, split deterministically by a
    # fixed-point accumulator. Only backends with a device read path
    # accept a nonzero mix (they pass reads_supported=True below).
    read_fraction: float = 0.0
    # Closed-loop clients per lane: each issues one request, waits for
    # its commit, thinks think_time ticks, re-issues. 0 = open loop.
    closed_window: int = 0
    think_time: int = 0
    # Per-lane FIFO backlog bound (open-loop shaping): arrivals beyond
    # it are SHED (counted, never silently queued without bound).
    backlog_cap: int = 1024
    # Traced CONFLICT-DENSITY knob for the dependency-graph backends
    # (bpaxos; epaxos under general_deps): the probability that two
    # concurrent commands interfere, i.e. the edge density of the
    # adjacency bitmask ``ops/depgraph.py`` executes. None = the
    # backend's own static knob (no state leaf). Set, it rides
    # :class:`WorkloadState` like ``rate`` — quantized to 16ths on
    # device (:func:`conflict_k16`), so the whole [conflict x load]
    # surface is ONE compile, swept by :func:`set_conflict_rate`.
    conflict_rate: Optional[float] = None
    # "trace": a recorded open-loop arrival schedule replayed by an
    # in-graph cursor — trace_len events, one int32 word per event
    # (``packing.encode_trace``: delta-encoded tick << 16 | lane), the
    # words themselves installed as STATE (``load_trace``) so swapping
    # traces never recompiles. Up to trace_chunk events fire per tick;
    # a hotter instant defers the excess to the next tick (FIFO order
    # and exactly-once accounting preserved — the backlog absorbs it).
    trace_len: int = 0
    trace_chunk: int = 8

    # -- structural predicates (all trace-time Python bools) ------------

    @property
    def shaped(self) -> bool:
        """An arrival process is configured (arrivals are drawn)."""
        return self.arrival != "saturate"

    @property
    def closed(self) -> bool:
        return self.closed_window > 0

    @property
    def active(self) -> bool:
        """Any shaping engaged (the tick helpers run iff this holds)."""
        return self.shaped or self.closed

    @property
    def has_reads(self) -> bool:
        return self.shaped and self.read_fraction > 0.0

    @property
    def has_conflict(self) -> bool:
        """The traced conflict knob is engaged (a state leaf exists).
        Independent of ``active``: conflict density shapes the
        DEPENDENCY structure, not the arrival process."""
        return self.conflict_rate is not None

    @classmethod
    def none(cls) -> "WorkloadPlan":
        """The structural no-op plan: every helper compiles to the
        identity, every state leaf is zero-sized, and XLA emits the
        exact pre-workload program."""
        return cls()

    def validate(self, reads_supported: bool = False) -> None:
        """Config-time validation; every backend's ``__post_init__``
        calls this (backends with a device read path pass
        ``reads_supported=True`` when the read ring is configured)."""
        assert self.arrival in ARRIVALS, (
            f"workload.arrival={self.arrival!r} not in {ARRIVALS}"
        )
        assert self.rate >= 0.0
        if self.arrival == "trace":
            assert self.trace_len > 0, (
                "workload.arrival='trace' needs trace_len > 0 (the "
                "event count load_trace will install)"
            )
            assert 1 <= self.trace_chunk <= 2**10
            assert self.closed_window == 0, (
                "a recorded trace IS the arrival schedule — closed-loop "
                "gating would rewrite it (use an open-loop trace)"
            )
        elif self.shaped:
            assert self.rate > 0.0, (
                "a shaped arrival process needs workload.rate > 0"
            )
            # The fixed-point accumulator and the Poisson sampler both
            # want per-lane-per-tick means far below the int32 emission
            # bound; 2^14 is orders beyond any sane per-lane load.
            assert self.rate * max(self.burst_mult, 1.0) < 2**14
        assert 0.0 <= self.read_fraction < 1.0
        if self.read_fraction > 0.0:
            assert self.shaped, "read_fraction needs an arrival process"
            assert reads_supported, (
                "workload.read_fraction > 0 but this backend/config has "
                "no device read path; backends with one: "
                + ", ".join(READ_BACKENDS)
                + " (enable its read ring, or set read_fraction=0)"
            )
        if self.arrival == "bursty":
            assert 1 <= self.burst_len <= self.burst_every
            assert self.burst_mult > 0.0
        if self.arrival == "diurnal":
            assert len(self.phases) >= 1 and self.phase_len >= 1
            assert all(p > 0.0 for p in self.phases)
        assert self.closed_window >= 0
        assert 0 <= self.think_time < 2**14
        assert self.backlog_cap >= 1
        assert self.zipf_s >= 0.0
        if self.conflict_rate is not None:
            assert 0.0 <= self.conflict_rate <= 1.0, (
                "workload.conflict_rate is a probability"
            )

    # -- serialization (one schema with harness/workload.py) ------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["phases"] = list(self.phases)
        d["type"] = "device_plan"
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadPlan":
        d = {k: v for k, v in d.items() if k != "type"}
        d["phases"] = tuple(d.get("phases", ()))
        return cls(**d)


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class WorkloadState:
    """Device-resident shaping state, carried in every batched
    backend's ``*State`` (lane axis L = the backend's proposer axis).
    Every leaf is ZERO-SIZED for the features a plan leaves off — a
    ``WorkloadPlan.none()`` state is all-empty, adds zero ops, and
    keeps the scan carry bit-identical to the pre-workload program.
    Counters are int32 (the dtype policy's accumulator width); the two
    traced sweep scalars are float32 (``widen_state`` passes floats
    through, so narrow/widened replays stay bit-identical)."""

    # Traced sweep axes: the offered rate, and a traced FaultPlan's
    # [drop, dup, crash, revive] Bernoulli rates (faults.make_rates).
    rate: jnp.ndarray  # [] float32 offered rate (shaped) | [0]
    fault_rates: jnp.ndarray  # [4] float32 (faults.traced) | [0]
    conflict: jnp.ndarray  # [] float32 conflict density (has_conflict) | [0]
    # Arrival bookkeeping (shaped).
    acc: jnp.ndarray  # [L] int32 16-bit fixed-point accumulator
    racc: jnp.ndarray  # [L] int32 read-split accumulator | [0]
    backlog: jnp.ndarray  # [L] int32 queued (arrived, unadmitted) writes
    cum_ring: jnp.ndarray  # [L, WAIT_BINS] int32 cumulative-arrival ring
    adm_total: jnp.ndarray  # [L] int32 cumulative admissions
    # Closed loop (closed_window > 0).
    in_flight: jnp.ndarray  # [L] int32 outstanding requests | [0]
    idle: jnp.ndarray  # [L] int32 clients ready to issue | [0]
    ready_ring: jnp.ndarray  # [L, think_time] int32 think expiries | [L, 0]
    # Trace replay (arrival == "trace"): the recorded schedule itself is
    # STATE — packing.encode_trace words installed by load_trace, the
    # cursor and its absolute clock advanced in-graph — so swapping a
    # million-event trace never recompiles.
    trace: jnp.ndarray  # [trace_len] int32 (dt << 16 | lane) | [0]
    trace_cursor: jnp.ndarray  # [] int32 next unfired event | [0]
    trace_next: jnp.ndarray  # [] int32 absolute tick of that event | [0]
    # Cumulative accounting (plan.active).
    offered: jnp.ndarray  # [] int32 write arrivals drawn | [0]
    admitted: jnp.ndarray  # [] int32 admissions | [0]
    completed: jnp.ndarray  # [] int32 completions | [0]
    shed: jnp.ndarray  # [] int32 arrivals shed at backlog_cap | [0]
    wait_sum: jnp.ndarray  # [] int32 total queue-wait ticks | [0]
    wait_hist: jnp.ndarray  # [WAIT_BINS] int32 queue-wait bins | [0]


def make_state(
    plan: WorkloadPlan,
    lanes: int,
    faults: FaultPlan = FaultPlan.none(),
) -> WorkloadState:
    """The backend's per-lane shaping state (+ the traced fault-rate
    scalars when ``faults.traced``). Leaves for disabled features are
    zero-sized so the none plan carries nothing."""
    z32 = jnp.int32
    Ls = lanes if plan.shaped else 0
    Lc = lanes if plan.closed else 0
    TH = plan.think_time if (plan.closed and plan.think_time) else 0
    NT = plan.trace_len if plan.arrival == "trace" else 0
    scalar = () if plan.active else (0,)
    sh_scalar = () if plan.shaped else (0,)
    tr_scalar = () if NT else (0,)
    return WorkloadState(
        rate=(
            jnp.full((), plan.rate, jnp.float32)
            if plan.shaped
            else jnp.zeros((0,), jnp.float32)
        ),
        fault_rates=faults_mod.make_rates(faults),
        conflict=(
            jnp.full((), plan.conflict_rate, jnp.float32)
            if plan.has_conflict
            else jnp.zeros((0,), jnp.float32)
        ),
        acc=jnp.zeros((Ls,), z32),
        racc=jnp.zeros((Ls if plan.has_reads else 0,), z32),
        backlog=jnp.zeros((Ls,), z32),
        cum_ring=jnp.zeros((Ls, WAIT_BINS if Ls else 0), z32),
        adm_total=jnp.zeros((Ls,), z32),
        in_flight=jnp.zeros((Lc,), z32),
        idle=jnp.full((Lc,), plan.closed_window, z32),
        ready_ring=jnp.zeros((Lc, TH), z32),
        trace=jnp.zeros((NT,), z32),
        trace_cursor=jnp.zeros(tr_scalar, z32),
        trace_next=jnp.zeros(tr_scalar, z32),
        offered=jnp.zeros(scalar, z32),
        admitted=jnp.zeros(scalar, z32),
        completed=jnp.zeros(scalar, z32),
        shed=jnp.zeros(sh_scalar, z32),
        wait_sum=jnp.zeros(sh_scalar, z32),
        wait_hist=jnp.zeros((WAIT_BINS if plan.shaped else 0,), z32),
    )


def workload_key(key: jnp.ndarray) -> jnp.ndarray:
    """The per-tick workload stream. Callers must only derive this when
    the plan is active so the inactive path touches no keys at all."""
    return jax.random.fold_in(key, WORKLOAD_SALT)


# ---------------------------------------------------------------------------
# Tick-side helpers. Call order inside a backend's tick:
#     writes, reads, wls = begin(plan, wls, key, t, lanes)
#     cap = admission(plan, wls, writes)            # clamp the propose knob
#     ... existing propose path admits `actual` [L] entries ...
#     wls = finish(plan, wls, t, writes, actual, completed_per_lane)
# ---------------------------------------------------------------------------


def _modulation(plan: WorkloadPlan, t) -> jnp.ndarray:
    """Traced scalar rate multiplier at tick ``t`` (1.0 for the
    unmodulated processes)."""
    if plan.arrival == "bursty":
        in_burst = jnp.mod(t, plan.burst_every) < plan.burst_len
        return jnp.where(in_burst, plan.burst_mult, 1.0).astype(
            jnp.float32
        )
    if plan.arrival == "diurnal":
        sched = jnp.asarray(plan.phases, jnp.float32)
        phase = jnp.mod(t // plan.phase_len, len(plan.phases))
        return jnp.take(sched, phase)
    return jnp.float32(1.0)


def begin(
    plan: WorkloadPlan,
    wls: WorkloadState,
    key: jnp.ndarray,
    t,
    lanes: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, WorkloadState]:
    """Draw this tick's per-lane arrivals and release think-expired
    closed-loop clients. Returns ``(writes [L], reads [L], wls')``.
    Inactive plan: zero-sized arrays, state untouched, no PRNG."""
    if not plan.active:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, wls
    acc, racc = wls.acc, wls.racc
    trace_cursor, trace_next = wls.trace_cursor, wls.trace_next
    if plan.arrival == "trace":
        # Replay the recorded schedule: decode up to trace_chunk events
        # at the cursor, fire the prefix whose absolute clocks have
        # arrived, scatter-add them onto their lanes. No PRNG; the
        # extra (+1-th) decode seeds the post-advance cursor clock.
        CH, NT = plan.trace_chunk, plan.trace_len
        idx = trace_cursor + jnp.arange(CH + 1, dtype=jnp.int32)
        valid = idx < NT
        words = jnp.take(wls.trace, jnp.clip(idx, 0, NT - 1))
        dt, lane = packing.decode_trace(words)
        # The cursor event's delta is already folded into trace_next
        # (load_trace seeds it; each advance re-seeds it below).
        times = trace_next + jnp.cumsum(dt.at[0].set(0))
        # Nondecreasing times + prefix validity => fire is a PREFIX, so
        # the cursor advance keeps FIFO order and fires each event
        # exactly once. A tick hotter than the chunk defers the tail.
        fire = valid & (times <= t)
        n_fire = jnp.sum(fire[:CH].astype(jnp.int32))
        arrivals = jnp.zeros((lanes,), jnp.int32).at[
            jnp.where(fire[:CH], lane[:CH], 0)
        ].add(fire[:CH].astype(jnp.int32))
        trace_cursor = trace_cursor + n_fire
        trace_next = jnp.take(times, n_fire)  # stable when exhausted
    elif plan.shaped:
        lam = (
            wls.rate
            * _modulation(plan, t)
            * jnp.asarray(zipf_weights(lanes, plan.zipf_s))
        )  # [L] float32
        if plan.arrival == "constant":
            # Deterministic 16-bit fixed-point emission: exact long-run
            # rate, zero variance, no PRNG.
            lam_fp = jnp.round(lam * _FP_ONE).astype(jnp.int32)
            acc = acc + lam_fp
            arrivals = acc >> 16
            acc = acc & (_FP_ONE - 1)
        else:
            arrivals = jax.random.poisson(
                workload_key(key), lam, (lanes,), dtype=jnp.int32
            )
    else:
        arrivals = jnp.zeros((lanes,), jnp.int32)
    if plan.has_reads:
        rf_fp = max(1, int(round(plan.read_fraction * _FP_ONE)))
        racc = racc + arrivals * rf_fp
        reads = racc >> 16
        racc = racc & (_FP_ONE - 1)
        writes = arrivals - reads
    else:
        reads = jnp.zeros((0,), jnp.int32)
        writes = arrivals
    idle, ready_ring = wls.idle, wls.ready_ring
    if plan.closed and plan.think_time:
        # Think-expiry release: clients whose think clock lands on this
        # ring slot become ready to issue (the offset-clock encoding of
        # think_time — one ring column per residual tick).
        TH = plan.think_time
        slot = (jnp.arange(TH, dtype=jnp.int32) == jnp.mod(t, TH))
        idle = idle + jnp.sum(
            jnp.where(slot[None, :], ready_ring, 0), axis=1
        )
        ready_ring = jnp.where(slot[None, :], 0, ready_ring)
    return writes, reads, dataclasses.replace(
        wls, acc=acc, racc=racc, idle=idle, ready_ring=ready_ring,
        trace_cursor=trace_cursor, trace_next=trace_next,
    )


def admission(
    plan: WorkloadPlan, wls: WorkloadState, writes: jnp.ndarray
) -> jnp.ndarray:
    """[L] int32 admission cap for this tick — the max entries each
    lane's propose path may take. Backends clamp their static
    proposals-per-tick knob with it (``rank <= cap[:, None]`` /
    ``minimum(cap, space)``): the backend ring may still admit fewer;
    :func:`finish` accounts the ACTUAL count. Callers only reach this
    when the plan is active."""
    assert plan.active
    if plan.shaped:
        demand = wls.backlog + writes
        if plan.closed:
            demand = jnp.minimum(demand, wls.idle)
        return demand
    # Pure closed loop: every idle client issues immediately.
    return wls.idle


def finish(
    plan: WorkloadPlan,
    wls: WorkloadState,
    t,
    writes: jnp.ndarray,
    admitted: jnp.ndarray,
    completed: jnp.ndarray,
) -> WorkloadState:
    """End-of-tick accounting: backlog/shed, the exact FIFO queue-wait
    histogram, and the closed-loop window. ``admitted`` is the ACTUAL
    per-lane count the propose path took this tick (``<= admission``);
    ``completed`` is the per-lane count of workload entries whose
    commit the client observed this tick."""
    if not plan.active:
        return wls
    new = {}
    admitted = admitted.astype(jnp.int32)
    completed = completed.astype(jnp.int32)
    if plan.shaped:
        # Backlog update: admission drains the FIFO head; arrivals
        # beyond backlog_cap shed from the tail (newest first), so the
        # FIFO indexing of everything that stays is untouched.
        backlog_mid = wls.backlog + writes - admitted
        shed_l = jnp.maximum(backlog_mid - plan.backlog_cap, 0)
        new["backlog"] = backlog_mid - shed_l
        arr_eff = writes - shed_l
        new["offered"] = wls.offered + jnp.sum(arr_eff)
        new["shed"] = wls.shed + jnp.sum(shed_l)
        # Cumulative-arrival ring: slot t % WAIT_BINS holds the total
        # surviving arrivals through tick t.
        prev_total = wls.adm_total + wls.backlog  # == old cum total
        cum_now = prev_total + arr_eff  # [L]
        wslot = (
            jnp.arange(WAIT_BINS, dtype=jnp.int32) == jnp.mod(t, WAIT_BINS)
        )
        cum_ring = jnp.where(
            wslot[None, :], cum_now[:, None], wls.cum_ring
        )
        new["cum_ring"] = cum_ring
        # Exact FIFO wait binning: the admitted index interval
        # [adm_before, adm_after) intersected with each past tick's
        # arrival-count interval (C_{j+1}, C_j] gives the count of
        # entries admitted now that waited exactly j ticks (j ==
        # WAIT_BINS-1 saturates: it absorbs everything older than the
        # ring).
        adm_before = wls.adm_total
        adm_after = adm_before + admitted
        new["adm_total"] = adm_after
        j = jnp.arange(WAIT_BINS, dtype=jnp.int32)
        Cs = jnp.take(cum_ring, jnp.mod(t - j, WAIT_BINS), axis=1)
        lo = jnp.concatenate(
            [Cs[:, 1:], jnp.zeros_like(Cs[:, :1])], axis=1
        )
        counts = jnp.clip(
            jnp.minimum(adm_after[:, None], Cs)
            - jnp.maximum(adm_before[:, None], lo),
            0,
            None,
        )  # [L, WAIT_BINS]
        new["wait_hist"] = wls.wait_hist + jnp.sum(counts, axis=0)
        new["wait_sum"] = wls.wait_sum + jnp.sum(counts * j[None, :])
    else:
        new["offered"] = wls.offered + jnp.sum(admitted)
    new["admitted"] = wls.admitted + jnp.sum(admitted)
    new["completed"] = wls.completed + jnp.sum(completed)
    if plan.closed:
        new["in_flight"] = wls.in_flight + admitted - completed
        idle = wls.idle - admitted
        if plan.think_time:
            TH = plan.think_time
            slot2 = (
                jnp.arange(TH, dtype=jnp.int32)
                == jnp.mod(t + TH, TH)  # == t % TH: released NEXT lap
            )
            new["ready_ring"] = wls.ready_ring + jnp.where(
                slot2[None, :], completed[:, None], 0
            )
        else:
            idle = idle + completed
        new["idle"] = idle
    return dataclasses.replace(wls, **new)


def invariants_ok(plan: WorkloadPlan, wls: WorkloadState) -> jnp.ndarray:
    """Traced scalar bool: the shaping bookkeeping is conserved —
    closed-loop lanes never exceed their window (in_flight + idle +
    thinking == closed_window, all nonnegative) and open-loop backlogs
    respect their bound. True (a constant) when the plan is inactive;
    every backend merges this into ``check_invariants``."""
    ok = jnp.asarray(True)
    if plan.closed:
        thinking = jnp.sum(wls.ready_ring, axis=1)
        ok = (
            ok
            & jnp.all(wls.in_flight >= 0)
            & jnp.all(wls.idle >= 0)
            & jnp.all(
                wls.in_flight + wls.idle + thinking == plan.closed_window
            )
        )
    if plan.shaped:
        ok = (
            ok
            & jnp.all(wls.backlog >= 0)
            & jnp.all(wls.backlog <= plan.backlog_cap)
            & jnp.all(wls.adm_total >= 0)
        )
    if plan.arrival == "trace":
        ok = (
            ok
            & (wls.trace_cursor >= 0)
            & (wls.trace_cursor <= plan.trace_len)
        )
    return ok


# ---------------------------------------------------------------------------
# Host-side sweep + reporting helpers.
# ---------------------------------------------------------------------------


def set_rate(wls: WorkloadState, rate: float) -> WorkloadState:
    """The offered-load sweep axis: a new traced rate, same compile."""
    assert wls.rate.shape == (), (
        "set_rate needs a shaped plan (arrival != 'saturate')"
    )
    return dataclasses.replace(
        wls, rate=jnp.full((), rate, jnp.float32)
    )


def set_conflict_rate(wls: WorkloadState, rate: float) -> WorkloadState:
    """The conflict-density sweep axis: a new traced conflict rate,
    same compile (the [conflict x load] surface of the depgraph
    backends replays one program)."""
    assert wls.conflict.shape == (), (
        "set_conflict_rate needs a plan with conflict_rate set"
    )
    return dataclasses.replace(
        wls, conflict=jnp.full((), rate, jnp.float32)
    )


def conflict_k16(plan: WorkloadPlan, wls: WorkloadState, static_rate: float):
    """The conflict knob as an int32 numerator over 16 — the shape the
    bit-sliced sampler (``ops/depgraph.bernoulli_words_k16``) consumes.
    Traced (from ``wls.conflict``) when the plan carries a conflict
    rate; otherwise the backend's static knob, quantized the same way,
    as a trace-time Python int."""
    if plan.has_conflict:
        return jnp.clip(
            jnp.round(wls.conflict * 16.0), 0, 16
        ).astype(jnp.int32)
    return int(round(static_rate * 16))


def set_fault_rates(
    wls: WorkloadState,
    drop: float = 0.0,
    dup: float = 0.0,
    crash: float = 0.0,
    revive: float = 0.0,
) -> WorkloadState:
    """The fault-rate sweep axis of a ``FaultPlan(traced=True)`` config:
    new traced Bernoulli rates, same compile."""
    assert wls.fault_rates.shape == (4,), (
        "set_fault_rates needs a FaultPlan(traced=True) config"
    )
    return dataclasses.replace(
        wls,
        fault_rates=jnp.asarray(
            [drop, dup, crash, revive], jnp.float32
        ),
    )


def load_trace(wls: WorkloadState, words) -> WorkloadState:
    """Install a host-encoded arrival trace (``packing.encode_trace``
    words) into a trace-plan state and rewind the cursor. The trace is
    STATE, not a trace constant: every install replays the same
    compiled program (pinned by ``tests/test_workload.py``)."""
    import numpy as np

    words = np.asarray(words, np.int32)
    assert wls.trace.shape == words.shape, (
        f"trace has {words.shape[0]} events but the plan was built "
        f"with trace_len={wls.trace.shape[0]} (the event count is "
        "static; size the plan to the trace)"
    )
    lanes = wls.backlog.shape[0]
    lane_ids = words.view(np.uint32) & np.uint32(packing.TRACE_LANE_MASK)
    assert int(lane_ids.max()) < lanes, (
        f"trace lane id {int(lane_ids.max())} out of range for "
        f"{lanes} lanes"
    )
    return dataclasses.replace(
        wls,
        trace=jnp.asarray(words),
        trace_cursor=jnp.zeros((), jnp.int32),
        trace_next=jnp.full(
            (), packing.trace_first_time(words), jnp.int32
        ),
    )


def hist_percentile(hist, q: float) -> int:
    """Nearest-rank percentile of an integer histogram (bin index =
    value). -1 on an empty histogram. One algorithm repo-wide: this is
    the device_get wrapper over the pure-numpy core the SLO engine
    alarms on (``monitoring/slo.py`` — lazily imported; the monitoring
    layer stays jax-free)."""
    from frankenpaxos_tpu.monitoring.slo import hist_p99

    return hist_p99(jax.device_get(hist), q)


def summary(plan: WorkloadPlan, wls: WorkloadState) -> dict:
    """Host roll-up of the shaping state (one coalesced pull):
    cumulative offered/admitted/completed/shed, queue depth, window
    occupancy, and queue-wait percentiles."""
    wls = jax.device_get(wls)
    out = {"active": plan.active, "arrival": plan.arrival}
    if plan.has_conflict:
        out["conflict_rate"] = float(wls.conflict)
    if not plan.active:
        return out
    out.update(
        offered=int(wls.offered),
        admitted=int(wls.admitted),
        completed=int(wls.completed),
    )
    if plan.shaped:
        import numpy as np

        out.update(
            rate=float(wls.rate),
            shed=int(wls.shed),
            wait_sum_ticks=int(wls.wait_sum),
            queue_depth=int(np.sum(wls.backlog)),
            queue_wait_p50_ticks=hist_percentile(wls.wait_hist, 0.50),
            queue_wait_p99_ticks=hist_percentile(wls.wait_hist, 0.99),
        )
    if plan.arrival == "trace":
        out.update(
            trace_len=plan.trace_len,
            trace_cursor=int(wls.trace_cursor),
        )
    if plan.closed:
        import numpy as np

        out.update(
            closed_window=plan.closed_window,
            in_flight=int(np.sum(wls.in_flight)),
            idle=int(np.sum(wls.idle)),
        )
    return out
