"""Batched MultiPaxos as a single XLA program.

The write path of compartmentalized MultiPaxos (SURVEY.md §3.2: Leader →
ProxyLeader → acceptor group `slot % G` → quorum count → Chosen → replica
``executeLog``) re-designed TPU-first. Instead of per-actor objects and
point-to-point messages, the whole cluster is struct-of-arrays state:

  * ``G`` acceptor groups of ``A = 2f+1`` acceptors — the replica axis of
    the simulation is ``G×A`` acceptors (10k+), vectorized elementwise and
    shardable over a device mesh along ``G`` (slots are partitioned
    ``slot % G`` exactly like ProxyLeader.scala:190, so the write path
    needs NO cross-group communication; only the global executed watermark
    is a collective).
  * Each group owns a ring of ``W`` in-flight slots (the BufferMap /
    in-flight-window of the reference, with backpressure).
  * "The network" is device memory: a message send is a write of an
    arrival tick into an array; delivery is an equality test against the
    tick counter; message loss and latency are PRNG-sampled per message
    (the FakeTransport nondeterminism model, massively parallel).
  * Quorum counting (ProxyLeader.handlePhase2b, f+1-of-A) is a sum over
    the acceptor axis; thrifty quorum choice is a top-(f+1) selection of
    PRNG scores; ballot checks compare per-acceptor round arrays.
  * Replica execution (Replica.executeLog's contiguous-prefix hot loop)
    is a masked min-reduction over the ring (no gather, no prefix scan).

Array layout is ACCEPTOR-MAJOR: per-acceptor-per-slot arrays are
``[A, G, W]`` (and per-acceptor arrays ``[A, G]``), NOT ``[G, W, A]``.
XLA tiles the two minor-most dims of an int32 array to (8, 128) sublanes ×
lanes on TPU; a minor acceptor axis of size ``A = 2f+1 = 3`` would be
padded 3 → 128 — a ~42× physical-memory and HBM-bandwidth blowup on the
four largest state arrays. Acceptor-major puts (G, W) minor, which tiles
densely, and makes the acceptor axis a tiny static leading loop — exactly
the layout :func:`frankenpaxos_tpu.ops.fused_vote_quorum` (the Pallas
fused kernel for tick steps 1-2, enabled by ``use_pallas``) wants, so the
kernel boundary needs no transposes.

One ``tick`` is a pure function ``(state, t, key) -> state`` compiled once;
``run_ticks`` wraps it in ``lax.scan``. Multi-seed property testing = vmap
over a seed axis; multi-chip = shard_map over the group axis (see
``frankenpaxos_tpu.parallel``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_CLOCK,
    DTYPE_COUNT,
    DTYPE_ROUND,
    DTYPE_STATUS,
    INF,
    INF16,
    LAT_BINS,
    age_clock,
    bit_delivered,
    bit_latency,
    sample_latency,
    sample_quorum,
)
# Submodule import (not `from frankenpaxos_tpu.ops import ...` package
# attrs): ops/__init__ imports tpu.common, whose package init imports
# the backends — attribute access on the half-initialized ops package
# would be a circular-import error, while the registry submodule loads
# cleanly from either entry point.
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import elastic as elastic_mod
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
from frankenpaxos_tpu.tpu import packing
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.elastic import ElasticPlan, ElasticState
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan, LifecycleState
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState

# Slot status codes.
EMPTY = 0
PROPOSED = 1
CHOSEN = 2

# Value ids. Real values are >= 0 (the global command sequence number
# ``slot * G + group``); NOOP_VALUE marks a slot repaired to a noop by a
# new leader (Leader.scala:314-329 safeValue returns Noop when no acceptor
# voted); NO_VALUE marks unset.
NO_VALUE = -1
NOOP_VALUE = -2

# Read op status codes (the read ring; see the "Reads" section of tick).
R_EMPTY = 0
R_WAIT = 1  # linearizable: MaxSlotRequest quorum outstanding
R_BOUND = 2  # target slot known; waiting for the executed watermark
R_SENT = 3  # watermark passed; reply in flight to the client

READ_MODES = ("linearizable", "sequential", "eventual")

# Matchmaker reconfiguration phases (per group).
RC_NORMAL = 0
RC_MATCHING = 1  # MatchA sent; awaiting an f+1 MatchB quorum
RC_PHASE1 = 2  # Phase1a sent to the OLD config; awaiting f+1 Phase1bs

# Saturation floor of the head-relative acc_max_slot delta (the
# wrap-safe half of ROADMAP PR 1 follow-up (a)): an acceptor that has
# not voted within the last 2^14 retired slots of its group
# reconstructs as head - 2^14 — old enough that the MaxSlot wave max
# ignores it unless every sampled quorum member is equally stale.
AMS_FLOOR = -(2**14)


@dataclasses.dataclass(frozen=True)
class BatchedMultiPaxosConfig:
    """Static (compile-time) simulation parameters."""

    f: int = 1
    num_groups: int = 4  # G: acceptor groups; total acceptors = G * (2f+1)
    window: int = 32  # W: in-flight slots per group (ring capacity)
    slots_per_tick: int = 4  # K: new proposals per group per tick
    lat_min: int = 1  # message latency in ticks (uniform sample)
    lat_max: int = 3
    drop_rate: float = 0.0  # per-message Bernoulli loss
    retry_timeout: int = 16  # re-send Phase2a to the FULL group after this
    thrifty: bool = True  # send Phase2a to f+1 random acceptors, else all
    # Closed workload: stop proposing once each group has allocated this
    # many slots (None = open workload, propose forever).
    max_slots_per_group: Optional[int] = None
    # Kernel-layer dispatch policy (ops/registry.py): every hot plane of
    # the tick — vote/quorum, phase-1 promise aggregation, and the
    # choose/watermark/propose/retry dispatch plane — routes through
    # ops.dispatch, which picks the fused Pallas kernel, interpret mode,
    # or the pure-jnp reference per this knob. Off the reference path
    # the vote + dispatch planes additionally fuse into the whole-tick
    # MEGAKERNEL (multipaxos_fused_tick: one Pallas grid program per
    # tick, offset clocks aged in-kernel on the fast path); disable=
    # ("multipaxos_fused_tick",) restores the per-plane kernels. The
    # default ("auto") is Pallas on TPU backends, reference elsewhere.
    kernels: KernelPolicy = KernelPolicy()
    # Legacy flags, folded into the policy by ops.registry.policy_of:
    # use_pallas=True ⇒ mode="on" (kernel on TPU, interpret elsewhere)
    # with pallas_block_g as the block size.
    use_pallas: bool = False
    pallas_block_g: int = 256  # group-axis block per kernel invocation
    # The read path: device-resident ReadBatchers (ReadBatcher.scala:
    # 239-338 Size/Adaptive batching, Acceptor.scala:222-237
    # handleBatchMaxSlotRequest, Replica.scala:455-529 deferred batches).
    # Every group hosts a read batcher; each tick, read_rate client reads
    # arrive at EACH group's batcher and form one batch (so read load
    # scales with G, the way the reference adds ReadBatcher nodes).
    # Linearizable batches ride a shared per-tick MaxSlot probe WAVE —
    # one random f+1 read quorum of every group, the reference's Adaptive
    # scheme ("when we receive a BatchMaxSlotReply, we'll trigger the
    # batch") collapsed onto the device: all batchers reuse the same
    # quorum round, and the whole batch binds to the max global voted
    # slot the wave observed, then drains behind the executed watermark.
    # One wave amortizes over G * read_rate reads — the batching
    # economics that let ReadBatcher.scala scale reads past writes.
    # Modes: "linearizable" (wave + watermark), "sequential" (bind to the
    # client's largest-seen slot, Client.scala:300-305), "eventual"
    # (execute immediately, Replica.scala:645-654).
    read_rate: int = 0  # client reads per GROUP per tick (0 = reads off)
    read_window: int = 0  # batch/wave ring slots (NW; 0 = reads off)
    read_mode: str = "linearizable"
    # Device-side failure detection + elections (heartbeat/Participant.
    # scala:72-209, election round-robin of roundsystem ClassicRoundRobin):
    # each group has C leader candidates; round r is owned by candidate
    # r % C. With fail_rate > 0, alive candidates die (and dead ones
    # revive at revive_rate) by PRNG inside the tick; followers count
    # ticks of owner silence in a heartbeat-miss counter and, at
    # heartbeat_timeout, elect the next alive candidate — round bump plus
    # phase-1 repair happen INSIDE the compiled scan, no host injection.
    fail_rate: float = 0.0  # per-candidate per-tick death probability
    revive_rate: float = 0.05  # per-dead-candidate per-tick revival prob
    heartbeat_timeout: int = 8  # silent ticks before an election
    num_leader_candidates: int = 3  # C
    # Enable the election machinery without PRNG fault injection (for
    # deterministic tests that kill candidates by editing leader_alive).
    device_elections: bool = False
    # Device-side replica state machine + client table (the batched
    # Replica.executeCommand, Replica.scala:305-344: client-table dedup,
    # then stateMachine.run; KeyValueStore.scala + ClientTable.scala).
    # "kv": each group's replica applies its retired commands to a
    # per-group KV shard (key = id % kv_keys, last-writer-wins — ids are
    # slot-monotone so the winner is a scatter-max) with per-client
    # exactly-once dedup. Slots round-robin over num_clients pseudonyms
    # (client of per-group slot s is s % num_clients); with dup_rate > 0
    # a newly proposed slot re-issues its client's LATEST command id (a
    # client re-sending an un-acked op) and the client table must filter
    # the re-execution.
    state_machine: str = "none"  # "none" | "kv"
    kv_keys: int = 64  # keys per group's KV shard
    num_clients: int = 8  # client pseudonyms per group
    dup_rate: float = 0.0  # P(a fresh slot re-issues its client's last id)
    # Device-side Matchmaker reconfiguration (BASELINE config 4;
    # matchmakermultipaxos/Matchmaker.scala + Reconfigurer.scala): every
    # reconfigure_every ticks each group swaps in a fresh acceptor
    # configuration bound to the next round (the i/i+1 semantics) via a
    # REAL message exchange inside the compiled scan: MatchA/MatchB to a
    # 2f+1 matchmaker group (f+1 quorum), then Phase1a/Phase1b against
    # the OLD configuration — safe values come from the first f+1
    # Phase1bs to arrive (a true read quorum, not an oracle read of all
    # acceptors). Proposals stall while a reconfiguration is in flight
    # (the throughput dip the churn sweep measures); the old
    # configuration is retained until the executed watermark passes the
    # slots it may have chosen (the GC pipeline).
    reconfigure_every: int = 0  # 0 = off
    # Unified in-graph fault injection (tpu/faults.py): extra message
    # drops, eager duplicates, delivery-delay jitter on the Phase2a/
    # Phase2b/retry planes (UDP semantics — the retry timers restore
    # liveness), crash/revive merged into the leader-candidate
    # machinery, and an acceptor-axis partition with a scheduled heal.
    # FaultPlan.none() is a structural no-op: XLA emits the exact
    # pre-fault program and runs stay bit-identical.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): open-loop arrival
    # processes + Zipf lane skew + read/write mix shaping the per-group
    # admission cap, and closed-loop clients with an outstanding-request
    # window per group. The traced offered rate (and a traced
    # FaultPlan's rates) live in State.workload, so [workload x fault]
    # grids sweep one compiled program. WorkloadPlan.none() is a
    # structural no-op (saturation — the pre-plan behavior).
    workload: WorkloadPlan = WorkloadPlan.none()
    # Production-lifecycle subsystem (tpu/lifecycle.py): watermark-
    # driven window rotation (the slot numbering rebases in place once
    # every group's head clears the quantum — unbounded serve runs in
    # a constant int32 horizon), the exactly-once client session table
    # (duplicate re-submissions answered from the per-lane cache
    # without re-proposing), and the traced acceptor-membership epoch
    # axis (the serve control plane swaps/shrinks/grows the live
    # acceptor set with zero recompiles; the i/i+1 handoff rides the
    # multipaxos_p1_promise plane). LifecyclePlan.none() is a
    # structural no-op: default runs stay bit-identical.
    lifecycle: LifecyclePlan = LifecyclePlan.none()
    # Elastic capacity (tpu/elastic.py): the proposer-group axis is a
    # PADDED plane behind a traced active-count — arrivals re-route
    # over the first N live lanes (a traced modulus, zero recompiles),
    # so the SLO autoscaler grows admission capacity under duress and
    # shrinks it on the trough (drain-then-deactivate: a deactivating
    # group first stops receiving, then drops out once its window and
    # backlog are empty). ElasticPlan.none() is a structural no-op.
    elastic: ElasticPlan = ElasticPlan.none()
    # Bit-packed hot narrow planes (tpu/packing.py, the dtype policy's
    # sub-byte tier): carry the 2-bit status/rb_status planes and the
    # session-table occupancy bits packed into int32 words in the scan
    # carry. The tick unpacks ONCE at entry and packs ONCE at exit, so
    # every tick equation (and kernel plane) sees the identical int8
    # arrays — packed runs are bit-identical to the unpacked twin by
    # construction (tests/test_packing.py, 3 seeds).
    pack_planes: bool = False

    @property
    def num_matchmakers(self) -> int:
        return 2 * self.f + 1

    @property
    def group_size(self) -> int:
        return 2 * self.f + 1

    @property
    def num_acceptors(self) -> int:
        return self.num_groups * self.group_size

    @property
    def rotation_alignment(self) -> int:
        """Smallest rotation shift that is an EXACT renumbering: a
        multiple of the ring width W (ring positions and the client
        round-robin are slot mod W / mod NC with NC | W) and — under
        the kv state machine — sized so the id shift ``align * G`` is a
        multiple of kv_keys (key residency is id mod KV)."""
        import math as _math

        align = self.window
        if self.state_machine == "kv":
            align *= self.kv_keys // _math.gcd(
                self.kv_keys, self.window * self.num_groups
            )
        return align

    def __post_init__(self):
        assert self.f >= 1
        assert self.window >= 2 * self.slots_per_tick
        # heartbeat_miss saturates at the timeout in DTYPE_COUNT (int16);
        # miss + 1 must also fit, so the bound is 2**15 - 1 exclusive.
        assert self.heartbeat_timeout < 2**15 - 1
        assert 1 <= self.lat_min <= self.lat_max
        # Offset clocks (DTYPE_CLOCK) must hold any pending arrival:
        # lat_max plus the fault plan's jitter/penalty is the largest
        # offset ever written (retries re-write, they don't accumulate).
        assert (
            self.lat_max + self.faults.jitter + self.faults.drop_penalty
            < INF16
        )
        assert 0.0 <= self.drop_rate < 1.0
        assert 0.0 <= self.fail_rate < 1.0
        assert 0.0 <= self.revive_rate <= 1.0
        self.faults.validate(axis=self.group_size)
        self.workload.validate(reads_supported=self.read_rate > 0)
        self.lifecycle.validate(align=self.rotation_alignment)
        self.elastic.validate({"groups": self.num_groups})
        if self.elastic.active:
            # Elastic routing steers ARRIVALS over the live lanes: it
            # needs an open-loop shaped arrival process (closed-loop
            # clients are lane-pinned; saturation has no arrivals).
            assert self.workload.shaped and not self.workload.closed, (
                "elastic 'groups' needs an open-loop shaped workload "
                "(arrival process on, closed_window=0)"
            )
        if self.lifecycle.reconfig:
            # Both machineries bump rounds and re-promise; the traced
            # epoch axis replaces the static schedule, not joins it.
            assert self.reconfigure_every == 0, (
                "lifecycle.reconfig and reconfigure_every are mutually "
                "exclusive reconfiguration machineries"
            )
        if self.lifecycle.compaction:
            # The closed-workload cap compares next_slot against an
            # absolute budget; rebasing next_slot would silently extend
            # it.
            assert self.max_slots_per_group is None, (
                "lifecycle.rotate_every needs an open workload "
                "(max_slots_per_group=None)"
            )
        self.kernels.validate()
        assert self.read_mode in READ_MODES
        assert self.state_machine in ("none", "kv")
        if self.state_machine == "kv":
            assert self.kv_keys >= 1 and self.num_clients >= 1
            assert self.window % self.num_clients == 0, (
                "the per-client within-batch dedup reshapes the ring to "
                "[G, W/NC, NC]; pick num_clients dividing window"
            )
            assert 0.0 <= self.dup_rate < 1.0
        else:
            assert self.dup_rate == 0.0, "dup_rate needs state_machine='kv'"
        if self.read_rate:
            # A wave slot is reused every read_window ticks; a wave lives
            # at most 2*lat_max ticks (request leg + reply leg), so the
            # ring must outlast it.
            assert self.read_window >= 2 * self.lat_max + 2, (
                "read_window must exceed a wave round-trip "
                f"(need >= {2 * self.lat_max + 2})"
            )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedMultiPaxosState:
    """Struct-of-arrays cluster state, acceptor-major (module docstring).
    Shapes: [G] groups, [G, W] ring slots, [A, G, W] per-acceptor votes,
    [A, G] acceptors."""

    # Leader / proposer.
    leader_round: jnp.ndarray  # [G] current round (shared leader, per group)
    next_slot: jnp.ndarray  # [G] next per-group slot sequence number
    head: jnp.ndarray  # [G] lowest non-retired per-group slot number

    # Ring slots.
    status: jnp.ndarray  # [G, W] EMPTY | PROPOSED | CHOSEN
    slot_value: jnp.ndarray  # [G, W] value proposed for the slot (NO_VALUE)
    propose_tick: jnp.ndarray  # [G, W] first proposal tick (for latency)
    last_send: jnp.ndarray  # [G, W] last Phase2a send tick (for retries)
    chosen_tick: jnp.ndarray  # [G, W] tick the quorum formed (INF if not)
    chosen_round: jnp.ndarray  # [G, W] round the quorum formed in (-1 if not)
    chosen_value: jnp.ndarray  # [G, W] value the quorum chose (NO_VALUE)
    replica_arrival: jnp.ndarray  # [G, W] tick Chosen reaches replicas

    # Acceptors. The two message planes are OFFSET CLOCKS (DTYPE_CLOCK,
    # tpu/common.py): "arrival - t", 0 = arrives this tick, INF16 =
    # never, aged by one each tick via age_clock — the wrap-safe int16
    # delta encoding of the HBM pass (ROADMAP PR 1 follow-up (a)).
    acc_round: jnp.ndarray  # [A, G] per-acceptor promised round
    p2a_arrival: jnp.ndarray  # [A, G, W] Phase2a offset clock (INF16 = never)
    p2b_arrival: jnp.ndarray  # [A, G, W] Phase2b offset clock at counter
    vote_round: jnp.ndarray  # [A, G, W] round of the vote (-1 = none)
    vote_value: jnp.ndarray  # [A, G, W] value of the vote (NO_VALUE = none)

    # Execution / stats.
    executed: jnp.ndarray  # [G] per-group retired (executed) slot count
    committed: jnp.ndarray  # [] total slots chosen (cumulative)
    retired: jnp.ndarray  # [] total slots executed+retired (cumulative)
    lat_sum: jnp.ndarray  # [] sum of commit latencies (ticks)
    lat_hist: jnp.ndarray  # [LAT_BINS] commit latency histogram

    # Failure detection / elections (inert while cfg.fail_rate == 0).
    leader_alive: jnp.ndarray  # [C, G] candidate liveness
    heartbeat_miss: jnp.ndarray  # [G] ticks of owner silence
    elections: jnp.ndarray  # [] device-side elections (cumulative)

    # Matchmaker reconfiguration (inert while cfg.reconfigure_every == 0).
    # RC_NORMAL -> RC_MATCHING (MatchA/MatchB quorum) -> RC_PHASE1
    # (Phase1a/Phase1b quorum against the old config) -> RC_NORMAL.
    recon_phase: jnp.ndarray  # [G] RC_* phase
    config_epoch: jnp.ndarray  # [G] completed reconfigurations
    # Round/epoch the in-flight reconfiguration installs, CAPTURED when
    # the exchange starts: stragglers processed after p1_done must use
    # the values their messages were sent with, not the bumped ones.
    rc_round: jnp.ndarray  # [G]
    rc_epoch: jnp.ndarray  # [G]
    mm_epoch: jnp.ndarray  # [M, G] matchmaker's recorded epoch
    matcha_arrival: jnp.ndarray  # [M, G] MatchA arrival tick (INF)
    matchb_arrival: jnp.ndarray  # [M, G] MatchB arrival tick (INF)
    rc_p1a_arrival: jnp.ndarray  # [A, G] Phase1a arrival at OLD acceptors
    rc_p1b_arrival: jnp.ndarray  # [A, G] Phase1b arrival back at leader
    gc_watermark: jnp.ndarray  # [G] old config retired once head >= this
    old_live: jnp.ndarray  # [G] old configuration not yet GCd
    reconfigs: jnp.ndarray  # [] completed reconfigurations (cumulative)
    configs_gcd: jnp.ndarray  # [] old configs garbage-collected

    # Replica state machine + client table (zero-width when
    # cfg.state_machine == "none"). KV = kv_keys, NC = num_clients.
    kv_val: jnp.ndarray  # [G, KV] id of the last write to the key (NO_VALUE)
    ct_last: jnp.ndarray  # [G, NC] client table: largest executed id (-1)
    client_last_issued: jnp.ndarray  # [G, NC] client's latest issued id (-1)
    slot_is_dup: jnp.ndarray  # [G, W] provenance: slot re-issues a prior id
    sm_applied: jnp.ndarray  # [] commands applied to the state machine
    dups_filtered: jnp.ndarray  # [] re-executions the client table filtered
    dups_seen: jnp.ndarray  # [] retired real slots flagged as duplicates

    # Read path (all zero-sized when cfg.read_window == 0). NW = wave /
    # batch ring slots; global slot numbering is s*G + g. Per-group
    # ReadBatchers ([G, NW] rb_* arrays, sharded with the group axis)
    # ride a shared MaxSlot probe wave ([NW] + [A, G, NW] arrays).
    # acc_max_slot is DELTA-ENCODED relative to the group head (int16:
    # votes land in [head, head+W), and the delta ages by n_retire as
    # the head advances, saturating at AMS_FLOOR — wrap-safe like the
    # offset clocks). Absolute slot = head + delta while unsaturated.
    acc_max_slot: jnp.ndarray  # [A, G] head-relative max voted slot
    max_chosen_global: jnp.ndarray  # [] max global slot ever chosen (-1)
    client_watermark: jnp.ndarray  # [] client's largest-seen global slot (-1)
    wave_issue: jnp.ndarray  # [NW] wave launch tick (INF = slot free)
    req_arrival: jnp.ndarray  # [A, G, NW] MaxSlotRequest offset clock (INF16)
    resp_slot: jnp.ndarray  # [A, G, NW] BatchMaxSlotReply payload (global)
    resp_arrival: jnp.ndarray  # [A, G, NW] MaxSlotReply offset clock (INF16)
    rb_status: jnp.ndarray  # [G, NW] R_EMPTY | R_WAIT | R_BOUND | R_SENT
    rb_count: jnp.ndarray  # [G, NW] client reads carried by the batch
    rb_wave: jnp.ndarray  # [G, NW] wave ring slot the batch rides (-1)
    rb_issue: jnp.ndarray  # [G, NW] batch formation tick (INF)
    rb_target: jnp.ndarray  # [G, NW] bound global slot (-1 = none yet)
    rb_floor: jnp.ndarray  # [G, NW] max_chosen_global at issue (lin check)
    rb_reply_arrival: jnp.ndarray  # [G, NW] batch reply arrival (INF)
    reads_done: jnp.ndarray  # [] completed reads (cumulative)
    reads_shed: jnp.ndarray  # [] reads dropped by batcher backpressure
    read_lat_sum: jnp.ndarray  # [] sum of read latencies (read-weighted)
    read_lat_hist: jnp.ndarray  # [LAT_BINS] read latency histogram
    read_lin_violations: jnp.ndarray  # [] reads bound below their floor

    # Workload-engine shaping state (tpu/workload.py: backlog, closed
    # window, traced rate scalars; all-empty under WorkloadPlan.none()).
    workload: WorkloadState

    # Production-lifecycle state (tpu/lifecycle.py: rotation counters,
    # the [G, S] session table, the traced membership mask + epoch;
    # all-empty under LifecyclePlan.none()).
    lifecycle: LifecycleState

    # Elastic-capacity state (tpu/elastic.py: traced active/target
    # group counts + resize books; all-empty under ElasticPlan.none()).
    elastic: ElasticState

    # Device-side per-tick metric ring (tpu/telemetry.py contract).
    telemetry: Telemetry


def _pack_status(cfg, plane: jnp.ndarray) -> jnp.ndarray:
    """Status-plane storage form: packed int32 words under
    ``cfg.pack_planes``, the int8 plane itself otherwise."""
    return packing.pack_status(plane) if cfg.pack_planes else plane


def _unpack_status(cfg, words: jnp.ndarray, size: int) -> jnp.ndarray:
    """Inverse of :func:`_pack_status` (identity when unpacked)."""
    return (
        packing.unpack_status(words, size) if cfg.pack_planes else words
    )


def init_state(cfg: BatchedMultiPaxosConfig) -> BatchedMultiPaxosState:
    G, W, A = cfg.num_groups, cfg.window, cfg.group_size
    RW = cfg.read_window
    return BatchedMultiPaxosState(
        leader_round=jnp.zeros((G,), DTYPE_ROUND),
        next_slot=jnp.zeros((G,), jnp.int32),
        head=jnp.zeros((G,), jnp.int32),
        status=_pack_status(cfg, jnp.zeros((G, W), DTYPE_STATUS)),
        slot_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        propose_tick=jnp.full((G, W), INF, jnp.int32),
        last_send=jnp.full((G, W), INF, jnp.int32),
        chosen_tick=jnp.full((G, W), INF, jnp.int32),
        chosen_round=jnp.full((G, W), -1, DTYPE_ROUND),
        chosen_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        replica_arrival=jnp.full((G, W), INF, jnp.int32),
        acc_round=jnp.zeros((A, G), DTYPE_ROUND),
        p2a_arrival=jnp.full((A, G, W), INF16, DTYPE_CLOCK),
        p2b_arrival=jnp.full((A, G, W), INF16, DTYPE_CLOCK),
        vote_round=jnp.full((A, G, W), -1, DTYPE_ROUND),
        vote_value=jnp.full((A, G, W), NO_VALUE, jnp.int32),
        executed=jnp.zeros((G,), jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        retired=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        leader_alive=jnp.ones((cfg.num_leader_candidates, G), bool),
        heartbeat_miss=jnp.zeros((G,), DTYPE_COUNT),
        elections=jnp.zeros((), jnp.int32),
        recon_phase=jnp.zeros((G,), DTYPE_STATUS),
        config_epoch=jnp.zeros((G,), DTYPE_ROUND),
        rc_round=jnp.zeros((G,), DTYPE_ROUND),
        rc_epoch=jnp.zeros((G,), DTYPE_ROUND),
        mm_epoch=jnp.zeros((cfg.num_matchmakers, G), DTYPE_ROUND),
        matcha_arrival=jnp.full((cfg.num_matchmakers, G), INF, jnp.int32),
        matchb_arrival=jnp.full((cfg.num_matchmakers, G), INF, jnp.int32),
        rc_p1a_arrival=jnp.full((A, G), INF, jnp.int32),
        rc_p1b_arrival=jnp.full((A, G), INF, jnp.int32),
        gc_watermark=jnp.full((G,), -1, jnp.int32),
        old_live=jnp.zeros((G,), bool),
        reconfigs=jnp.zeros((), jnp.int32),
        configs_gcd=jnp.zeros((), jnp.int32),
        kv_val=jnp.full(
            (G, cfg.kv_keys if cfg.state_machine == "kv" else 0),
            NO_VALUE,
            jnp.int32,
        ),
        ct_last=jnp.full(
            (G, cfg.num_clients if cfg.state_machine == "kv" else 0),
            -1,
            jnp.int32,
        ),
        client_last_issued=jnp.full(
            (G, cfg.num_clients if cfg.state_machine == "kv" else 0),
            -1,
            jnp.int32,
        ),
        slot_is_dup=jnp.zeros(
            (G, W if cfg.state_machine == "kv" else 0), bool
        ),
        sm_applied=jnp.zeros((), jnp.int32),
        dups_filtered=jnp.zeros((), jnp.int32),
        dups_seen=jnp.zeros((), jnp.int32),
        acc_max_slot=jnp.full((A, G), -1, jnp.int16),
        max_chosen_global=jnp.full((), -1, jnp.int32),
        client_watermark=jnp.full((), -1, jnp.int32),
        wave_issue=jnp.full((RW,), INF, jnp.int32),
        req_arrival=jnp.full((A, G, RW), INF16, DTYPE_CLOCK),
        resp_slot=jnp.full((A, G, RW), -1, jnp.int32),
        resp_arrival=jnp.full((A, G, RW), INF16, DTYPE_CLOCK),
        rb_status=_pack_status(cfg, jnp.zeros((G, RW), DTYPE_STATUS)),
        rb_count=jnp.zeros((G, RW), jnp.int32),
        rb_wave=jnp.full((G, RW), -1, jnp.int32),
        rb_issue=jnp.full((G, RW), INF, jnp.int32),
        rb_target=jnp.full((G, RW), -1, jnp.int32),
        rb_floor=jnp.full((G, RW), -1, jnp.int32),
        rb_reply_arrival=jnp.full((G, RW), INF, jnp.int32),
        reads_done=jnp.zeros((), jnp.int32),
        reads_shed=jnp.zeros((), jnp.int32),
        read_lat_sum=jnp.zeros((), jnp.int32),
        read_lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        read_lin_violations=jnp.zeros((), jnp.int32),
        workload=workload_mod.make_state(cfg.workload, G, cfg.faults),
        lifecycle=lifecycle_mod.make_state(
            cfg.lifecycle, G, acceptor_shape=(A, G),
            packed=cfg.pack_planes,
        ),
        elastic=elastic_mod.make_state(cfg.elastic),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedMultiPaxosConfig,
    state: BatchedMultiPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedMultiPaxosState:
    """One simulation tick: acceptors vote on arrivals, quorums form,
    replicas retire contiguous chosen prefixes, the leader proposes new
    slots and retries timed-out ones."""
    G, W, A = cfg.num_groups, cfg.window, cfg.group_size
    f = cfg.f
    # One random-bits sweep per shape feeds every sample via disjoint bit
    # fields (see common.bit_latency) — drawing separate randint/uniform
    # arrays per message kind made PRNG generation dominate the tick.
    k3, k2, k_extra, k_read, k_fail = jax.random.split(key, 5)
    bits3 = jax.random.bits(k3, (A, G, W))  # [0:8) p2b lat, [8:16) p2a lat,
    #                                         [16:24) retry lat, [24:32) p2b drop
    bits2 = jax.random.bits(k2, (G, W))  # [0:8) replica lat, [8:16) thrifty,
    #                                      [16:24) dup-injection draw
    p2b_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    p2a_lat = bit_latency(bits3, 8, cfg.lat_min, cfg.lat_max)
    retry_lat = bit_latency(bits3, 16, cfg.lat_min, cfg.lat_max)
    rep_lat = bit_latency(bits2, 0, cfg.lat_min, cfg.lat_max)
    p2b_delivered = bit_delivered(bits3, 24, cfg.drop_rate)
    # The extra sweep (drawn only when some feature needs it) feeds the
    # p2a drop field [0:8) AND, for general-f or membership-aware
    # thrifty, the quorum ranking scores [8:24) — disjoint fields, one
    # generation. The traced-membership axis (lifecycle.reconfig) needs
    # the ranking path even at f == 1: thrifty sampling must rank the
    # LIVE members first (see sample_quorum's live=).
    need_extra = cfg.drop_rate > 0.0 or (
        cfg.thrifty and (cfg.f > 1 or cfg.lifecycle.reconfig)
    )
    bits_extra = (
        jax.random.bits(k_extra, (A, G, W))
        if need_extra
        else jnp.zeros((A, G, W), jnp.uint32)
    )
    p2a_delivered = bit_delivered(bits_extra, 0, cfg.drop_rate)

    # Unified fault injection (tpu/faults.py): the plan's extra drops,
    # eager duplicates, delay jitter, and the acceptor-axis partition
    # fold into the SAME delivered/latency arrays the native drop_rate
    # feeds (UDP semantics — retries restore liveness after a heal).
    # The Chosen->replica broadcast and the read waves stay reliable
    # (the reference retries them like writes). FaultPlan.none() skips
    # everything here at trace time: no PRNG draw, no extra ops.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    retry_delivered = None
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, A)[:, None, None]
        f_del, p2a_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (A, G, W), p2a_lat, link_up,
            rates=frates,
        )
        p2a_delivered = p2a_delivered & f_del
        f_del, p2b_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (A, G, W), p2b_lat, link_up,
            rates=frates,
        )
        p2b_delivered = p2b_delivered & f_del
        retry_delivered, retry_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 2), (A, G, W), retry_lat, link_up,
            rates=frates,
        )

    # Message-plane latencies are written into OFFSET CLOCKS, so they
    # carry the clock dtype (values fit by the __post_init__ bound); on
    # the widen_state() int32 reference path the cast is a no-op, so
    # both paths replay bit-identically. rep_lat stays int32 — the
    # Chosen->replica arrival is an absolute tick.
    clock_dtype = state.p2a_arrival.dtype
    p2a_lat = p2a_lat.astype(clock_dtype)
    p2b_lat = p2b_lat.astype(clock_dtype)
    retry_lat = retry_lat.astype(clock_dtype)

    # Packed planes unpack ONCE here (identity when cfg.pack_planes is
    # off): every equation below reads the same int8 [G, W] array the
    # unpacked twin reads, so the two configs are bit-identical by
    # construction and only the scan carry's HBM bytes differ.
    status = _unpack_status(cfg, state.status, W)
    w_iota = jnp.arange(W, dtype=jnp.int32)  # ring positions
    # Global group ids, fed to the dispatch planes (fresh proposal
    # values encode slot * G + g): an explicit input rather than an
    # in-kernel iota, so a shard_map-lowered kernel sees ITS slice of
    # the global numbering instead of renumbering every shard from 0.
    g_ids_vec = jnp.arange(G, dtype=jnp.int32)

    # FaultPlan crash/revive merges into the leader-candidate machinery
    # (independent death sources compose); a none plan returns the
    # native rates unchanged, keeping this path bit-identical. The
    # STRUCTURAL gate is crash_on (a trace-time Python bool — traced
    # plans return traced eff rates, which must never be compared at
    # trace time); the megakernel routing below needs it too.
    crash_on = fp.has_crash or cfg.fail_rate > 0.0
    eff_fail, eff_revive = faults_mod.effective_process_rates(
        fp, cfg.fail_rate, cfg.revive_rate, rates=frates
    )

    # Megakernel routing (ops/multipaxos.py multipaxos_fused_tick): when
    # the policy resolves the fused-tick plane off the reference path,
    # the vote/quorum + dispatch planes below run as ONE Pallas grid
    # program — and on the fast path (no elections, no reconfiguration:
    # nothing between aging and the planes touches the clocks) the
    # per-tick offset-clock aging folds into the same kernel, so the two
    # largest [A, G, W] arrays are read from HBM exactly once per tick.
    # The megakernel SUBSUMES the vote/dispatch planes, so disabling
    # either of them must also force the multi-plane path (the disable
    # knob's "reference regardless of mode" contract).
    use_mega = all(
        ops_registry.resolve_mode(name, cfg) != "reference"
        for name in (
            "multipaxos_fused_tick",
            "multipaxos_vote_quorum",
            "multipaxos_dispatch",
        )
    )
    fuse_age = (
        use_mega
        and not (crash_on or cfg.device_elections)
        and not cfg.reconfigure_every
        # The traced-epoch leg (and its membership gating) writes the
        # clocks between aging and the planes, so the aging cannot
        # fold into the megakernel.
        and not cfg.lifecycle.reconfig
    )

    # Age the offset clocks ONCE, up front: after aging, an offset is
    # exactly ``arrival - t`` for the current tick (0 = arrives now),
    # the invariant every plane below tests against. Writes during this
    # tick store raw latencies (>= lat_min >= 1), which the next tick's
    # aging rebases — so a message written with latency L arrives
    # exactly L ticks later, matching the absolute-clock semantics bit
    # for bit. When the megakernel owns the aging, the raw clocks flow
    # straight into it (age=True) and XLA never emits a separate pass.
    if fuse_age:
        p2a_aged = state.p2a_arrival
        p2b_aged = state.p2b_arrival
    else:
        p2a_aged = age_clock(state.p2a_arrival)
        p2b_aged = age_clock(state.p2b_arrival)

    # ---- 0. Device-side failure detection + election (Participant.scala:
    # 72-209 heartbeat silence detection; ClassicRoundRobin round
    # ownership: round r belongs to candidate r % C). Everything below —
    # deaths, revivals, miss counters, the election, and the phase-1
    # repair — happens inside the compiled tick; no host involvement.
    leader_round = state.leader_round
    slot_value_in = state.slot_value
    p2a_in = p2a_aged
    p2b_in = p2b_aged
    last_send_in = state.last_send
    leader_alive = state.leader_alive
    heartbeat_miss = state.heartbeat_miss
    elections = state.elections
    owner_alive_now = None  # None = feature off, everyone alive
    if crash_on or cfg.device_elections:
        C = cfg.num_leader_candidates
        if crash_on:
            bits_f = jax.random.bits(k_fail, (C, G))  # [0:8) death, [8:16) rev
            dies = ~bit_delivered(bits_f, 0, eff_fail)
            revives = ~bit_delivered(bits_f, 8, eff_revive)
            leader_alive = jnp.where(leader_alive, ~dies, revives)
        owner = leader_round % C
        owner_alive = jnp.take_along_axis(leader_alive, owner[None, :], axis=0)[0]
        # Clamped at the timeout: only miss >= timeout is ever tested, so
        # the counter saturating there is observably identical to counting
        # forever — and it keeps DTYPE_COUNT overflow-safe through
        # arbitrarily long all-candidates-dead stretches.
        heartbeat_miss = jnp.where(
            owner_alive,
            0,
            jnp.minimum(heartbeat_miss + 1, cfg.heartbeat_timeout),
        )
        # Next alive candidate in round-robin order (C is tiny and
        # static: an unrolled first-match scan).
        delta = jnp.zeros((G,), leader_round.dtype)
        found = jnp.zeros((G,), bool)
        for d in range(1, C + 1):
            idx = (leader_round + d) % C
            cand = jnp.take_along_axis(leader_alive, idx[None, :], axis=0)[0]
            delta = jnp.where(~found & cand, d, delta)
            found = found | cand
        elect = (heartbeat_miss >= cfg.heartbeat_timeout) & found
        leader_round = leader_round + jnp.where(elect, delta, 0)
        heartbeat_miss = jnp.where(elect, 0, heartbeat_miss)
        elections = elections + jnp.sum(elect)
        # Phase-1 repair for elected groups — the registry's
        # multipaxos_p1_promise plane with an all-acceptors read (the
        # oracle-read election model: a superset of any f+1 read
        # quorum). Latency reuses the retry draw (retry_lat): repair and
        # retry are both Phase2a re-sends and a repaired slot
        # (last_send = t) cannot also time out this tick.
        slot_value_in, p2a_in, p2b_in, last_send_in = ops_registry.dispatch(
            "multipaxos_p1_promise",
            cfg,
            status,
            state.vote_round,
            state.vote_value,
            slot_value_in,
            p2a_in,
            p2b_in,
            last_send_in,
            elect,
            jnp.ones((A, G), bool),
            retry_lat,
            t,
        )
        # Post-election owner liveness gates proposals and retries below
        # (a dead leader proposes nothing; Leader.scala inactive state).
        owner2 = leader_round % C
        owner_alive_now = jnp.take_along_axis(
            leader_alive, owner2[None, :], axis=0
        )[0]

    # ---- 0.5 Matchmaker reconfiguration (Matchmaker.scala handleMatchA,
    # Reconfigurer.scala; see the config docstring). All message
    # exchanges are modeled arrivals inside this compiled tick.
    acc_round_in = state.acc_round
    vote_round_in = state.vote_round
    vote_value_in = state.vote_value
    recon_phase = state.recon_phase
    config_epoch = state.config_epoch
    rc_round = state.rc_round
    rc_epoch = state.rc_epoch
    mm_epoch = state.mm_epoch
    matcha_arrival = state.matcha_arrival
    matchb_arrival = state.matchb_arrival
    rc_p1a = state.rc_p1a_arrival
    rc_p1b = state.rc_p1b_arrival
    gc_watermark = state.gc_watermark
    old_live = state.old_live
    reconfigs = state.reconfigs
    configs_gcd = state.configs_gcd
    telem_phase1 = jnp.int32(0)  # phase-1-plane messages sent this tick
    if cfg.reconfigure_every:
        M = cfg.num_matchmakers
        k_rc = jax.random.fold_in(k_fail, 1)
        bits_m = jax.random.bits(k_rc, (M, G))  # [0:8) MatchA, [8:16) MatchB
        bits_a2 = jax.random.bits(
            jax.random.fold_in(k_rc, 1), (A, G)
        )  # [0:8) Phase1a lat, [8:16) Phase1b lat
        ma_lat = bit_latency(bits_m, 0, cfg.lat_min, cfg.lat_max)
        mb_lat = bit_latency(bits_m, 8, cfg.lat_min, cfg.lat_max)
        p1a_lat = bit_latency(bits_a2, 0, cfg.lat_min, cfg.lat_max)
        p1b_lat = bit_latency(bits_a2, 8, cfg.lat_min, cfg.lat_max)

        # (a) On schedule, the leader matchmakes the next configuration:
        # MatchA(epoch+1) to every matchmaker. The round/epoch this
        # exchange installs are CAPTURED here — stragglers of this wave
        # processed after p1_done must not read the bumped values — and
        # any straggler MatchB/Phase1b replies of the PREVIOUS wave are
        # discarded so they can't count toward this wave's quorums.
        due = (
            (recon_phase == RC_NORMAL)
            & ((t % cfg.reconfigure_every) == 0)
            & (t > 0)
        )
        rc_round = jnp.where(due, leader_round + 1, rc_round)
        rc_epoch = jnp.where(due, config_epoch + 1, rc_epoch)
        matchb_arrival = jnp.where(due[None, :], INF, matchb_arrival)
        rc_p1b = jnp.where(due[None, :], INF, rc_p1b)
        matcha_arrival = jnp.where(due[None, :], t + ma_lat, matcha_arrival)
        recon_phase = jnp.where(due, RC_MATCHING, recon_phase)

        # (b) Matchmakers process MatchA: record the epoch THE MESSAGE
        # CARRIES, reply MatchB carrying the prior configuration
        # (Matchmaker.scala handleMatchA stores the config bound to the
        # round).
        ma_now = matcha_arrival == t
        mm_epoch = jnp.where(ma_now, rc_epoch[None, :], mm_epoch)
        matchb_arrival = jnp.where(ma_now, t + mb_lat, matchb_arrival)
        matcha_arrival = jnp.where(ma_now, INF, matcha_arrival)

        # (c) An f+1 MatchB quorum starts phase 1 against the OLD
        # configuration (Reconfigurer: the new config is bound to round
        # i+1; the old one must be drained first).
        nmb = jnp.sum(matchb_arrival <= t, axis=0)
        mm_done = (recon_phase == RC_MATCHING) & (nmb >= f + 1)
        matchb_arrival = jnp.where(mm_done[None, :], INF, matchb_arrival)
        rc_p1a = jnp.where(mm_done[None, :], t + p1a_lat, rc_p1a)
        recon_phase = jnp.where(mm_done, RC_PHASE1, recon_phase)

        # (d) Old acceptors process Phase1a: PROMISE the round the
        # message was sent for (rc_round, captured at (a) — reading the
        # live leader_round here would over-promise a straggler past the
        # bumped round and lock it out of voting) — they stop voting in
        # the old round (the safety half of phase 1) — and reply with
        # their vote state.
        p1a_now = rc_p1a == t
        acc_round_in = jnp.maximum(
            acc_round_in,
            jnp.where(p1a_now, rc_round[None, :], -1),
        )
        rc_p1b = jnp.where(p1a_now, t + p1b_lat, rc_p1b)
        rc_p1a = jnp.where(p1a_now, INF, rc_p1a)

        # (e) The first f+1 Phase1bs form a TRUE read quorum: safe values
        # come from the learned acceptors only (they intersect every f+1
        # write quorum, so every chosen value is visible). Install the
        # new configuration: bump round+epoch, re-propose in-flight slots
        # to the fresh acceptors, clear their (never-cast) votes, and arm
        # the GC watermark.
        learned = rc_p1b <= t  # [A, G]
        np1b = jnp.sum(learned, axis=0)
        p1_done = (recon_phase == RC_PHASE1) & (np1b >= f + 1)
        # Latency reuses the retry bit field (retry_lat above): repair
        # re-sends and retries are both Phase2a sends, and a repaired
        # slot (last_send = t) cannot also time out this tick.
        (
            slot_value_in,
            p2a_in,
            p2b_in,
            last_send_in,
        ) = ops_registry.dispatch(
            "multipaxos_p1_promise",
            cfg,
            status, vote_round_in, vote_value_in, slot_value_in,
            p2a_in, p2b_in, last_send_in, p1_done, learned, retry_lat, t,
        )
        in_flight_rc = (status == PROPOSED) & p1_done[:, None]  # [G, W]
        vote_round_in = jnp.where(in_flight_rc[None, :, :], -1, vote_round_in)
        vote_value_in = jnp.where(
            in_flight_rc[None, :, :], NO_VALUE, vote_value_in
        )
        # max(), not overwrite: a device-side election can bump acceptors
        # past rc_round (via repair-Phase2a promises) while this exchange
        # is in flight; regressing acc_round below vote_round would break
        # promise monotonicity.
        acc_round_in = jnp.where(
            p1_done[None, :],
            jnp.maximum(acc_round_in, rc_round[None, :]),
            acc_round_in,
        )
        # max() keeps the round monotone if a device-side election bumped
        # it past rc_round while this exchange was in flight.
        leader_round = jnp.where(
            p1_done, jnp.maximum(rc_round, leader_round), leader_round
        )
        config_epoch = jnp.where(p1_done, rc_epoch, config_epoch)
        reconfigs = reconfigs + jnp.sum(p1_done)
        rc_p1b = jnp.where(p1_done[None, :], INF, rc_p1b)
        # The old configuration survives until every slot it may have
        # chosen retires (the Reconfigurer GC pipeline).
        gc_watermark = jnp.where(p1_done, state.next_slot, gc_watermark)
        old_live = old_live | p1_done
        recon_phase = jnp.where(p1_done, RC_NORMAL, recon_phase)
        # Phase-1-plane traffic this tick: MatchA fan-outs, MatchB
        # replies, Phase1a fan-outs to the old config, Phase1b replies.
        telem_phase1 = (
            M * jnp.sum(due)
            + jnp.sum(ma_now)
            + A * jnp.sum(mm_done)
            + jnp.sum(p1a_now)
        )

    # ---- 0.75 Traced acceptor reconfiguration (tpu/lifecycle.py): the
    # matchmaker i/i+1 handoff collapsed to one tick, driven by the
    # TRACED epoch + membership the serve control plane steers between
    # chunks (set_membership — zero recompiles). On an epoch switch:
    # round bump + phase-1 re-promise over the SAME p1_promise kernel
    # plane the elections use (oracle all-acceptor read, a superset of
    # any f+1 read quorum), in-flight votes clear and re-propose, and
    # old-epoch GC clears pending traffic to departed acceptors while
    # the epoch's slots drain behind the lifecycle GC watermark. Every
    # tick, the live mask gates the Phase2a/retry sends below, so
    # departed acceptors never receive (or cast) anything.
    lc = cfg.lifecycle
    lcs = state.lifecycle
    acc_mask_live = None
    if lc.reconfig:
        lc_switch = lifecycle_mod.reconfig_switch(lc, lcs)
        sw_g = jnp.broadcast_to(lc_switch, (G,))
        (
            slot_value_in,
            p2a_in,
            p2b_in,
            last_send_in,
        ) = ops_registry.dispatch(
            "multipaxos_p1_promise",
            cfg,
            status, vote_round_in, vote_value_in, slot_value_in,
            p2a_in, p2b_in, last_send_in, sw_g,
            jnp.ones((A, G), bool), retry_lat, t,
        )
        in_flight_lc = (status == PROPOSED) & sw_g[:, None]  # [G, W]
        vote_round_in = jnp.where(
            in_flight_lc[None, :, :], -1, vote_round_in
        )
        vote_value_in = jnp.where(
            in_flight_lc[None, :, :], NO_VALUE, vote_value_in
        )
        # i/i+1: the new epoch binds to the next round; promises stay
        # monotone (max), mirroring the matchmaker install step.
        leader_round = jnp.where(sw_g, leader_round + 1, leader_round)
        acc_round_in = jnp.where(
            lc_switch,
            jnp.maximum(acc_round_in, leader_round[None, :]),
            acc_round_in,
        )
        lcs = lifecycle_mod.reconfig_applied(
            lc, lcs, lc_switch, state.next_slot, state.head
        )
        acc_mask_live = lcs.acc_mask  # [A, G], post-switch
        not_member = ~acc_mask_live[:, :, None]
        # Old-epoch GC: departed acceptors' pending traffic clears —
        # the p2a blanket holds EVERY tick (a non-member never holds a
        # pending Phase2a, whatever plane wrote it), the p2b sweep on
        # the switch tick drops their in-flight replies on UNCHOSEN
        # slots only: chosen slots keep their old-epoch vote
        # certificates (p2b + vote state) until they retire, so
        # quorum_ok stays countable mid-handoff.
        p2a_in = jnp.where(not_member, INF16, p2a_in)
        p2b_in = jnp.where(
            lc_switch & not_member & (status != CHOSEN)[None, :, :],
            INF16,
            p2b_in,
        )
        # The re-promise fan-out is phase-1-plane traffic.
        telem_phase1 = telem_phase1 + A * G * lc_switch.astype(jnp.int32)

    # ---- [G]-space CONTROL for the planes below: proposal caps under
    # elections / reconfiguration / closed workloads, retry gates,
    # thrifty quorum membership. Decided OUTSIDE the planes and entering
    # as tiny per-group vectors (or [A, G, W] masks the PRNG already
    # produced), so every feature composes with the fused kernels — and
    # the whole-tick megakernel — unchanged. The WORKLOAD ENGINE
    # (tpu/workload.py) plugs in exactly here: under a shaping plan the
    # static slots_per_tick knob is replaced by the per-group admission
    # cap (arrival process x Zipf skew, FIFO backlog, closed-loop
    # window), and every other gate below composes on top.
    # ---- 0.8 Elastic capacity (tpu/elastic.py): apply any pending
    # resize, then re-route this tick's arrivals over the first
    # `min(active, target)` proposer lanes (a traced modulus — zero
    # recompiles). A deactivating group drops out of `active` only
    # once its window and backlog are EMPTY (drain-then-deactivate:
    # routing already steered new work away, so both drain naturally
    # and no in-flight work is lost).
    ela = cfg.elastic
    els = state.elastic
    n_resized = 0
    if ela.active:
        g_iota_e = jnp.arange(G, dtype=jnp.int32)
        g_tgt = elastic_mod.target_count(ela, els, "groups", G)
        deactivating = g_iota_e >= g_tgt
        lane_idle = (state.head == state.next_slot) & (
            wls.backlog == 0
        )
        els, n_resized = elastic_mod.apply(
            ela,
            els,
            {"groups": jnp.all(jnp.where(deactivating, lane_idle, True))},
        )
        g_route = elastic_mod.routing_count(ela, els, "groups", G)
    wl_writes = wl_reads = None
    if wl.active:
        wl_writes, wl_reads, wls = workload_mod.begin(
            wl, wls, key, t, G
        )
        if ela.active:
            wl_writes = elastic_mod.route_lanes(wl_writes, g_route)
            if wl.has_reads:
                wl_reads = elastic_mod.route_lanes(wl_reads, g_route)
        cap = workload_mod.admission(wl, wls, wl_writes)
    else:
        cap = jnp.full((G,), cfg.slots_per_tick, jnp.int32)
    if cfg.max_slots_per_group is not None:
        cap = jnp.minimum(
            cap, jnp.maximum(cfg.max_slots_per_group - state.next_slot, 0)
        )
    retry_ok = jnp.ones((G,), bool)
    if owner_alive_now is not None:
        # A dead leader proposes nothing and resends nothing
        # (Leader.scala inactive state) until an election installs a
        # live owner.
        cap = jnp.where(owner_alive_now, cap, 0)
        retry_ok = retry_ok & owner_alive_now
    if cfg.reconfigure_every:
        # A reconfiguring group stalls proposals (the churn throughput
        # dip) and old-round resends while phase 1 drains the old config.
        rc_normal = recon_phase == RC_NORMAL
        cap = jnp.where(rc_normal, cap, 0)
        retry_ok = retry_ok & rc_normal
    # Thrifty quorum selection (ThriftySystem / ProxyLeader.scala:187-197):
    # Phase2a goes to f+1 random acceptors of the slot's group. f==1 draws
    # from the always-generated bits2 sweep (bits_extra is all-zeros when
    # drop_rate == 0 and f == 1); general f ranks bits_extra fields [8:24)
    # (disjoint from its p2a drop field [0:8)). Under the traced
    # membership axis the sampling is MEMBERSHIP-AWARE: dead members
    # rank last, so a swapped-out acceptor is only sampled when fewer
    # than f+1 live members exist — commits/tick no longer dips by a
    # retry round across a swap (pinned by
    # tests/test_checkpoint.py::test_membership_aware_thrifty_no_dip).
    if cfg.thrifty:
        if acc_mask_live is not None:
            in_quorum = sample_quorum(
                bits_extra, 8, f, A, live=acc_mask_live[:, :, None]
            )
        else:
            bits_q = bits2[None] if f == 1 else bits_extra
            in_quorum = sample_quorum(bits_q, 8, f, A)
    else:
        in_quorum = jnp.ones((A, G, W), bool)
    send_ok = in_quorum & p2a_delivered
    retry_deliv = (
        retry_delivered
        if retry_delivered is not None
        else jnp.ones((A, G, W), bool)
    )
    if acc_mask_live is not None:
        # Membership gating: Phase2a fan-outs and full-group retries
        # reach live members only. The membership-aware sampling above
        # already ranks live members first, so this mask only bites
        # when fewer than f+1 members are live (no quorum exists and
        # the slot correctly stalls until the membership heals).
        send_ok = send_ok & acc_mask_live[:, :, None]
        retry_deliv = retry_deliv & acc_mask_live[:, :, None]

    # ---- 1-5. The tick hot path: acceptors vote on Phase2a arrivals
    # (Acceptor.handlePhase2a, Acceptor.scala:184-220), quorums form
    # (ProxyLeader.handlePhase2b, ProxyLeader.scala:217-258), then the
    # dispatch plane (quorum -> Chosen, the commit-watermark advance
    # with its retire-clears, leader proposals with their Phase2a
    # fan-out, and timeout resends). Under the megakernel policy this is
    # ONE registry plane — one Pallas grid program per tick, clocks aged
    # in-kernel on the fast path, vote state never leaving VMEM between
    # the vote and dispatch halves; otherwise the two per-plane kernels
    # (or their pure-jnp references) run back to back, which is the
    # exact pre-megakernel program the fused path is pinned against.
    # Either way the planes are dtype-native (int16 offset clocks, int16
    # rounds — no boundary casts) and emit the vote plane's Phase2b-send
    # counts plus each acceptor's max voted ordinal (the read path's
    # acc_max_slot feed), so telemetry and reads stay single-pass.
    if use_mega:
        (
            status,
            slot_value,
            propose_tick,
            last_send,
            chosen_tick,
            chosen_round,
            chosen_value,
            replica_arrival,
            p2a_arrival,
            p2b_arrival,
            vote_round,
            vote_value,
            head,
            next_slot,
            count,
            n_retire,
            newly_chosen,
            retire_mask,
            is_new,
            timed_out,
            latency,
            new_acc_round,
            ns_plane,
            max_ord,
        ) = ops_registry.dispatch(
            "multipaxos_fused_tick",
            cfg,
            p2a_in,
            acc_round_in,
            leader_round,
            slot_value_in,
            vote_round_in,
            vote_value_in,
            p2b_in,
            p2b_lat,
            p2b_delivered,
            state.head,
            status,
            state.propose_tick,
            last_send_in,
            state.chosen_tick,
            state.chosen_round,
            state.chosen_value,
            state.replica_arrival,
            state.next_slot,
            cap,
            retry_ok,
            send_ok,
            retry_deliv,
            p2a_lat,
            retry_lat,
            rep_lat,
            g_ids_vec,
            t,
            f=f,
            retry_timeout=cfg.retry_timeout,
            num_groups=G,
            age=fuse_age,
        )
    else:
        (
            vote_round,
            vote_value,
            p2b_arrival,
            new_acc_round,
            nvotes,
            ns_plane,
            max_ord,
        ) = ops_registry.dispatch(
            "multipaxos_vote_quorum",
            cfg,
            p2a_in,
            acc_round_in,
            leader_round,
            slot_value_in,
            vote_round_in,
            vote_value_in,
            p2b_in,
            p2b_lat,
            p2b_delivered,
            state.head,
        )
        (
            status,
            slot_value,
            propose_tick,
            last_send,
            chosen_tick,
            chosen_round,
            chosen_value,
            replica_arrival,
            p2a_arrival,
            p2b_arrival,
            vote_round,
            vote_value,
            head,
            next_slot,
            count,
            n_retire,
            newly_chosen,
            retire_mask,
            is_new,
            timed_out,
            latency,
        ) = ops_registry.dispatch(
            "multipaxos_dispatch",
            cfg,
            status,
            slot_value_in,
            state.propose_tick,
            last_send_in,
            state.chosen_tick,
            state.chosen_round,
            state.chosen_value,
            state.replica_arrival,
            p2a_in,
            p2b_arrival,
            vote_round,
            vote_value,
            nvotes,
            state.head,
            state.next_slot,
            leader_round,
            cap,
            retry_ok,
            send_ok,
            retry_deliv,
            p2a_lat,
            retry_lat,
            rep_lat,
            g_ids_vec,
            t,
            f=f,
            retry_timeout=cfg.retry_timeout,
            num_groups=G,
        )
    p2b_sends = jnp.sum(ns_plane)

    # Commit latency stats (from the plane's newly_chosen/latency masks).
    n_new = jnp.sum(newly_chosen)
    committed = state.committed + n_new
    lat_sum = state.lat_sum + jnp.sum(latency)
    bins = jnp.clip(latency, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly_chosen.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    ord_of_pos = (w_iota[None, :] - state.head[:, None]) % W  # [G, W]
    executed = state.executed + n_retire
    retired_total = state.retired + jnp.sum(n_retire)

    # Workload accounting: the plane's ACTUAL per-group admissions
    # (count — the ring may take fewer than the cap) drain the FIFO
    # backlog and occupy the closed-loop window; this tick's quorum
    # completions (the commit the client observes) release it. The
    # admitted entries' admission->commit latency is exactly the
    # newly_chosen/latency stats above — already accumulated into
    # lat_hist and the telemetry ring.
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count,
            jnp.sum(newly_chosen, axis=1),
        )

    if cfg.reconfigure_every:
        # GC: once the executed watermark passes every slot the old
        # configuration may have chosen, it retires (Reconfigurer GC).
        gc_now = old_live & (head >= gc_watermark)
        configs_gcd = configs_gcd + jnp.sum(gc_now)
        old_live = old_live & ~gc_now

    # ---- 3.5 Replica state machine + client table (Replica.executeCommand,
    # Replica.scala:305-344: client-table dedup, THEN stateMachine.run).
    # Runs on the pre-clear ring: ``chosen_value`` still holds this tick's
    # retiring commands. A command executes iff its id exceeds everything
    # its client executed before (ct_last, ClientTable.scala executed(),
    # plus an exact within-batch running max — see below); execution
    # applies it to the group's KV shard. Ids are valid only below the
    # slot_horizon_ok int32 bound (like the read path's global slot
    # numbering): past it the & 0x7FFFFFFF wrap breaks id monotonicity
    # and the invariant fails loudly rather than silently mis-deduping.
    kv_val = state.kv_val
    ct_last = state.ct_last
    client_last_issued = state.client_last_issued
    slot_is_dup = state.slot_is_dup
    sm_applied = state.sm_applied
    dups_filtered = state.dups_filtered
    dups_seen = state.dups_seen
    if cfg.state_machine == "kv":
        NC, KV = cfg.num_clients, cfg.kv_keys
        # The dispatch plane already retire-cleared the ring, so the
        # retiring commands are reconstructed from its masks: a retired
        # slot's pre-clear chosen_value is this tick's proposal value if
        # it was chosen this tick, else the carried chosen_value.
        cmd = jnp.where(newly_chosen, slot_value_in, state.chosen_value)
        real = retire_mask & (cmd >= 0)  # noops don't touch the SM
        client = jnp.where(real, (cmd // G) % NC, 0)
        last = jnp.take_along_axis(ct_last, client, axis=1)
        # A command executes iff its id exceeds everything its client has
        # executed before — in an earlier tick (ct_last) OR earlier in
        # this tick's contiguous batch. The within-batch part must handle
        # CHAINED re-issues (two dup slots carrying the same id can
        # retire together after a failover noop-repaired the original),
        # so it is an exact per-client exclusive running max over the
        # batch in execution order: slots at ordinals o and o+NC belong
        # to the same client (clients are slot % NC), so reshaping the
        # ordinal-ordered ids to [G, W/NC, NC] puts each client in a
        # column and the running max is a cummax down the rows.
        pos_of_ord = (state.head[:, None] + w_iota[None, :]) % W  # [G, W]
        ids_by_ord = jnp.take_along_axis(
            jnp.where(real, cmd, -1), pos_of_ord, axis=1
        )
        seq = ids_by_ord.reshape(G, W // NC, NC)
        run_max = jax.lax.cummax(seq, axis=1)
        prev_by_ord = jnp.concatenate(
            [jnp.full((G, 1, NC), -1, jnp.int32), run_max[:, :-1]], axis=1
        ).reshape(G, W)
        prev_same_client = jnp.take_along_axis(
            prev_by_ord, ord_of_pos, axis=1
        )
        executes = real & (cmd > jnp.maximum(last, prev_same_client))
        filtered = real & ~executes
        g_mat = jnp.broadcast_to(
            jnp.arange(G, dtype=jnp.int32)[:, None], (G, W)
        )
        ct_last = ct_last.at[g_mat, client].max(
            jnp.where(executes, cmd, -1)
        )
        # KV write is log-order last-writer-wins, NOT id-max: a chained
        # re-issue can execute an OLD id at a LATER log position than a
        # different client's newer id on the same key (the dup re-issued
        # after its original was noop-repaired), and sequential execution
        # keeps the later-in-log value. Per key the winner is the
        # executing command at the highest ordinal this tick — unique per
        # (group, key), so a scatter-max over winners-only is an exact
        # "set". Ticks retire in head order, so the cross-tick overwrite
        # is log-ordered too.
        key_of = jnp.where(executes, cmd % KV, 0)
        win_ord = (
            jnp.full((G, KV), -1, jnp.int32)
            .at[g_mat, key_of]
            .max(jnp.where(executes, ord_of_pos, -1))
        )
        is_winner = executes & (
            ord_of_pos == jnp.take_along_axis(win_ord, key_of, axis=1)
        )
        new_val = (
            jnp.full((G, KV), NO_VALUE, jnp.int32)
            .at[g_mat, key_of]
            .max(jnp.where(is_winner, cmd, NO_VALUE))
        )
        kv_val = jnp.where(win_ord >= 0, new_val, kv_val)
        sm_applied = sm_applied + jnp.sum(executes)
        dups_filtered = dups_filtered + jnp.sum(filtered)
        dups_seen = dups_seen + jnp.sum(retire_mask & slot_is_dup & (cmd >= 0))
        slot_is_dup = slot_is_dup & ~retire_mask

    group_ids = g_ids_vec[:, None]  # [G, 1]
    if cfg.state_machine == "kv":
        # Dup injection rides AFTER the dispatch plane: commands
        # round-robin over client pseudonyms, and a dup proposal
        # re-issues the client's LATEST id (the reference client
        # re-sends its one outstanding op, ClientMain.scala:190-323
        # pseudonyms) as of the last tick boundary. Only slot_value
        # changes — the plane's Phase2a sends carry no value, so the
        # override composes with the fused kernel exactly. last_issued
        # advances only on fresh proposals, so chained retries keep
        # re-issuing the same id.
        NC = cfg.num_clients
        delta = (w_iota[None, :] - state.next_slot[:, None]) % W  # [G, W]
        new_client = jnp.where(
            is_new, (state.next_slot[:, None] + delta) % NC, 0
        )
        prior = jnp.take_along_axis(client_last_issued, new_client, axis=1)
        if cfg.dup_rate > 0.0:
            dup_draw = ~bit_delivered(bits2, 16, cfg.dup_rate)
            is_dup = is_new & dup_draw & (prior >= 0)
        else:
            is_dup = jnp.zeros((G, W), bool)
        slot_value = jnp.where(is_dup, prior, slot_value)
        slot_is_dup = jnp.where(is_new, is_dup, slot_is_dup)
        g_mat4 = jnp.broadcast_to(
            jnp.arange(G, dtype=jnp.int32)[:, None], (G, W)
        )
        client_last_issued = client_last_issued.at[g_mat4, new_client].max(
            jnp.where(is_new & ~is_dup, slot_value, -1)
        )

    # ---- 6. Reads: device-resident ReadBatchers (ReadBatcher.scala:
    # 239-338 batching, Acceptor.scala:239-252 handleBatchMaxSlotRequest,
    # Replica.scala:455-529 deferred read batches draining behind the
    # executed watermark). Global slot numbering is s*G + g; the global
    # contiguous executed watermark is min_g(head_g*G + g). Each group
    # hosts a batcher; each tick every batcher forms one batch of
    # cfg.read_rate reads, and all linearizable batches ride the tick's
    # shared MaxSlot probe wave (one random f+1 read quorum of EVERY
    # group — Client.scala:851-933 semantics, so the bind is provably
    # linearizable, unlike the reference ReadBatcher's one-random-group
    # "+ numGroups - 1" heuristic with its own safety TODO). Reads are
    # modeled lossless (the reference retries them like writes).
    acc_max_slot = state.acc_max_slot
    max_chosen_global = state.max_chosen_global
    client_watermark = state.client_watermark
    wave_issue = state.wave_issue
    req_arrival = state.req_arrival
    resp_slot = state.resp_slot
    resp_arrival = state.resp_arrival
    rb_status = _unpack_status(cfg, state.rb_status, cfg.read_window)
    rb_count = state.rb_count
    rb_wave = state.rb_wave
    rb_issue = state.rb_issue
    rb_target = state.rb_target
    rb_floor = state.rb_floor
    rb_reply_arrival = state.rb_reply_arrival
    reads_done = state.reads_done
    reads_shed = state.reads_shed
    read_lat_sum = state.read_lat_sum
    read_lat_hist = state.read_lat_hist
    read_lin_violations = state.read_lin_violations
    if cfg.read_rate:
        NW = cfg.read_window
        # The read-wave planes are offset clocks like the write planes:
        # age once so 0 means "arrives now".
        req_arrival = age_clock(req_arrival)
        resp_arrival = age_clock(resp_arrival)
        kr_a, kr_b = jax.random.split(k_read)
        bits_r = jax.random.bits(kr_a, (A, G, NW))  # [0:8) req lat,
        #                       [8:16) resp lat, [16:32) quorum sampling
        bits_rg = jax.random.bits(kr_b, (G, NW))  # [0:8) batch reply lat
        req_lat = bit_latency(bits_r, 0, cfg.lat_min, cfg.lat_max).astype(
            clock_dtype
        )
        resp_lat = bit_latency(bits_r, 8, cfg.lat_min, cfg.lat_max).astype(
            clock_dtype
        )
        reply_lat = bit_latency(bits_rg, 0, cfg.lat_min, cfg.lat_max)

        # (a) Acceptor bookkeeping: a vote on per-group slot s raises that
        # acceptor's maxVotedSlot (Acceptor.scala:222-237 serves it from
        # vote state). Votes happened against the PRE-retire ring, and
        # the HEAD-RELATIVE delta of a vote at ordinal o is simply o —
        # which is exactly the vote plane's ``max_ord`` output (computed
        # inside the kernel pass, AMS_FLOOR where no vote), so reads no
        # longer re-derive the vote predicate in a second [A, G, W]
        # sweep: ``use_pallas + reads`` is single-pass again.
        slot_of_pos = state.head[:, None] + ord_of_pos  # [G, W] per-group slot
        acc_max_slot = jnp.maximum(
            acc_max_slot, max_ord.astype(acc_max_slot.dtype)
        )
        # Global floor for the linearizability check: the largest global
        # slot chosen so far (any read issued after this point must bind
        # at or above it — read/write quorum intersection).
        max_chosen_global = jnp.maximum(
            max_chosen_global,
            jnp.max(jnp.where(newly_chosen, slot_of_pos * G + group_ids, -1)),
        )

        # (b) BatchMaxSlotReplies: requests arriving now read the
        # acceptor's updated max voted slot in GLOBAL numbering (delta +
        # the group head it is relative to); replies travel back
        # (Acceptor.scala:239-252).
        req_now = req_arrival == 0  # [A, G, NW]
        g_row = jnp.arange(G, dtype=jnp.int32)[None, :]  # [1, G]
        abs_max = acc_max_slot + state.head[None, :]  # [A, G] int32
        global_acc = jnp.where(abs_max >= 0, abs_max * G + g_row, -1)
        resp_slot = jnp.where(req_now, global_acc[:, :, None], resp_slot)
        resp_arrival = jnp.where(req_now, resp_lat, resp_arrival)
        req_arrival = jnp.where(req_now, INF16, req_arrival)  # consumed

        # (c) Wave completion + bind: once every sampled acceptor of a
        # wave has replied, ALL batches riding that wave bind to the max
        # reply (the shared Adaptive-scheme quorum round; the max over a
        # quorum per group is Client.scala:851-933's bind rule). The
        # wave slot frees immediately — its lifetime is <= 2*lat_max,
        # which __post_init__ guarantees is under the ring period.
        any_outstanding = jnp.any(req_arrival != INF16, axis=(0, 1))  # [NW]
        any_pending = jnp.any(
            (resp_arrival != INF16) & (resp_arrival > 0), axis=(0, 1)
        )
        wave_ready = (wave_issue < INF) & ~any_outstanding & ~any_pending
        wave_val = jnp.max(
            jnp.where(resp_arrival != INF16, resp_slot, -1), axis=(0, 1)
        )  # [NW]
        # Batches ride the wave recorded at their formation (rb_wave);
        # batch ring rows and wave ring slots are decoupled so a batch
        # stalled behind the watermark doesn't block the row its tick's
        # wave index happens to map to.
        wv = jnp.clip(rb_wave, 0, NW - 1)
        bind_now = (rb_status == R_WAIT) & jnp.take(wave_ready, wv)
        batch_val = jnp.take(wave_val, wv)  # [G, NW]
        rb_target = jnp.where(bind_now, batch_val, rb_target)
        read_lin_violations = read_lin_violations + jnp.sum(
            jnp.where(bind_now & (batch_val < rb_floor), rb_count, 0)
        )
        rb_status = jnp.where(bind_now, R_BOUND, rb_status)
        wave_issue = jnp.where(wave_ready, INF, wave_issue)
        resp_slot = jnp.where(wave_ready[None, None, :], -1, resp_slot)
        resp_arrival = jnp.where(
            wave_ready[None, None, :], INF16, resp_arrival
        )

        # (d) Completion: a batch's reply leaves once the executed
        # watermark passes its target (Replica.scala:407-412 drains
        # deferred reads inside executeLog). The reply carries the slot
        # the batch actually EXECUTED at (watermark-1, >= target) — the
        # client's largestSeenSlots updates from executed slots, not
        # requested targets (Client.scala:300-305), which is what lets
        # sequential reads advance behind concurrent writes.
        watermark = jnp.min(head * G + jnp.arange(G, dtype=jnp.int32))
        can_send = (rb_status == R_BOUND) & (watermark > rb_target)
        # After the floor check at bind, rb_target's only remaining
        # consumer is the client watermark update below, so it can carry
        # the executed slot from here on.
        rb_target = jnp.where(can_send, watermark - 1, rb_target)
        rb_reply_arrival = jnp.where(can_send, t + reply_lat, rb_reply_arrival)
        rb_status = jnp.where(can_send, R_SENT, rb_status)
        done = (rb_status == R_SENT) & (rb_reply_arrival <= t)
        done_count = jnp.where(done, rb_count, 0)
        rlat = jnp.where(done, t - rb_issue, 0)
        reads_done = reads_done + jnp.sum(done_count)
        read_lat_sum = read_lat_sum + jnp.sum(rlat * done_count)
        rbins = jnp.clip(rlat, 0, LAT_BINS - 1)
        read_lat_hist = read_lat_hist + jax.ops.segment_sum(
            done_count.ravel(), rbins.ravel(), LAT_BINS
        )
        client_watermark = jnp.maximum(
            client_watermark, jnp.max(jnp.where(done, rb_target, -1))
        )
        rb_status = jnp.where(done, R_EMPTY, rb_status)
        rb_count = jnp.where(done, 0, rb_count)
        rb_target = jnp.where(done, -1, rb_target)
        rb_floor = jnp.where(done, -1, rb_floor)
        rb_issue = jnp.where(done, INF, rb_issue)
        rb_wave = jnp.where(done, -1, rb_wave)
        rb_reply_arrival = jnp.where(done, INF, rb_reply_arrival)

        # (e) Issue. Wave ring slot w = t mod NW hosts this tick's probe
        # wave; each group's batcher forms a batch of read_rate reads in
        # its FIRST free row (rows and wave slots are decoupled). A
        # group with every row occupied (watermark lag) sheds its reads —
        # batcher backpressure, counted honestly instead of silently
        # queued.
        wslot = (
            jnp.arange(NW, dtype=jnp.int32) == jnp.mod(t, NW)
        )  # [NW] one-hot
        empty_rb = rb_status == R_EMPTY  # [G, NW]
        rank_rb = jnp.cumsum(empty_rb.astype(jnp.int32), axis=1)
        can_batch = empty_rb & (rank_rb == 1)  # first free row per group
        if wl.has_reads:
            # Workload read/write mix: the batch carries this tick's
            # ACTUAL read arrivals for the group (Zipf-skewed, process-
            # shaped) instead of the static read_rate; groups with no
            # read arrivals form no batch, and arrivals to a backlogged
            # batcher shed as before.
            can_batch = can_batch & (wl_reads[:, None] > 0)
            reads_shed = reads_shed + jnp.sum(
                jnp.where(jnp.any(can_batch, axis=1), 0, wl_reads)
            )
            rb_count = jnp.where(can_batch, wl_reads[:, None], rb_count)
        else:
            reads_shed = reads_shed + cfg.read_rate * (
                G - jnp.sum(can_batch)
            )
            rb_count = jnp.where(can_batch, cfg.read_rate, rb_count)
        rb_issue = jnp.where(can_batch, t, rb_issue)
        rb_floor = jnp.where(can_batch, max_chosen_global, rb_floor)
        if cfg.read_mode == "linearizable":
            # Launch the shared wave: one random f+1 read quorum of
            # EVERY group (randomReadQuorum, QuorumSystem.scala:16-24).
            launch = wslot & (wave_issue == INF)  # [NW]
            in_rq = sample_quorum(bits_r, 16, f, A)
            send_req = launch[None, None, :] & in_rq
            req_arrival = jnp.where(send_req, req_lat, req_arrival)
            wave_issue = jnp.where(launch, t, wave_issue)
            rb_wave = jnp.where(can_batch, jnp.mod(t, NW), rb_wave)
            rb_status = jnp.where(can_batch, R_WAIT, rb_status)
        elif cfg.read_mode == "sequential":
            # The client's largest-seen slot (Client.scala:300-305). The
            # batched client is a read-only observer: its watermark
            # advances from its own completed reads (writes belong to
            # other, anonymous clients).
            rb_target = jnp.where(can_batch, client_watermark, rb_target)
            rb_status = jnp.where(can_batch, R_BOUND, rb_status)
        else:  # eventual: execute immediately (Replica.scala:645-654)
            rb_target = jnp.where(can_batch, -1, rb_target)
            rb_status = jnp.where(can_batch, R_BOUND, rb_status)

        # (f) Rebase the head-relative deltas: this tick retired
        # n_retire slots per group, so every delta shifts down with the
        # head it is measured from, saturating at AMS_FLOOR (stale
        # acceptors age out of the MaxSlot max instead of wrapping).
        acc_max_slot = jnp.maximum(
            acc_max_slot - n_retire[None, :], AMS_FLOOR
        ).astype(acc_max_slot.dtype)

    # ---- 6.5 Production lifecycle (tpu/lifecycle.py). Session table:
    # this tick's client-visible completions (the same per-group
    # quorum counts the workload engine's finish() receives — the
    # shared books behind the extended conservation invariant) record
    # into the [G, S] table, and duplicate re-submissions answer from
    # the cache on a DISJOINT PRNG stream — the protocol planes above
    # never see them, so exactly-once holds by construction. Rotation:
    # once every group's head clears the quantum (or the host latched
    # a force-rotation), this tick's shift is computed HERE — feeding
    # the telemetry ring's rotations column and leaving the span
    # sampler on the pre-roll base — and the slot planes rebase at the
    # very end of the tick.
    if lc.has_sessions:
        lcs = lifecycle_mod.sessions_step(
            lc, lcs, key, t, jnp.sum(newly_chosen, axis=1)
        )
    lc_shift = None
    lc_base = 0
    if lc.compaction:
        lc_base = lcs.rot_base
        # margin=W: the furthest back a LIVE id record can point
        # (client_last_issued references slots >= next_slot - NC with
        # NC | W), so every in-flight id survives the rebase exactly;
        # only the HISTORICAL tables (ct_last / kv_val) can reference
        # older slots, and those demote to the unset sentinel below.
        lc_shift, lcs = lifecycle_mod.rotation_shift(
            lc, lcs, jnp.min(head), cfg.rotation_alignment,
            margin=cfg.window,
        )

    # ---- 7. Telemetry (tpu/telemetry.py contract): every count is an
    # int32 reduction of a mask/counter the tick already computed for
    # its own bookkeeping, so with the default ring this adds register
    # adds plus one ring-row write; with a zero-width ring XLA removes
    # it all. Identical under use_pallas: only pre-kernel masks are
    # counted (the vote predicate stays kernel-internal).
    n_proposed = jnp.sum(count)  # [G]-space
    n_retries = jnp.sum(timed_out)
    if cfg.drop_rate > 0.0 or fp.messages_active:
        phase2_sends = jnp.sum(is_new[None, :, :] & send_ok)
        p2a_drops = jnp.sum(
            is_new[None, :, :] & in_quorum & ~p2a_delivered
        )
    else:
        # Lossless path: sample_quorum selects EXACTLY f+1 members (A
        # when non-thrifty) and every send is delivered, so the mask
        # sum equals quorum_size * proposals — counted in [G] space,
        # keeping the <2% overhead budget free of extra [A, G, W]
        # reductions on the flagship config.
        quorum_size = (f + 1) if cfg.thrifty else A
        phase2_sends = quorum_size * n_proposed
        p2a_drops = 0
    tel = record(
        state.telemetry,
        proposals=n_proposed,
        phase1_msgs=telem_phase1,
        # Exact phase-2 plane on BOTH kernel paths: Phase2a fan-outs +
        # full-group retries + the Phase2b replies (kernel output under
        # use_pallas, the live vote mask otherwise).
        phase2_msgs=phase2_sends + A * n_retries + p2b_sends,
        commits=n_new,
        executes=retired_total - state.retired,
        drops=p2a_drops,
        retries=n_retries,
        leader_changes=elections - state.elections,
        rotations=(
            (lc_shift > 0).astype(jnp.int32)
            if lc_shift is not None
            else 0
        ),
        resizes=n_resized,
        queue_depth=jnp.sum(next_slot - head),
        queue_capacity=G * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    # ---- 7.5 Span sampler (telemetry.record_spans): lifecycle
    # tick-stamps of a sampled reservoir of in-flight slots, recorded
    # from the masks the planes already emitted (is_new / Phase2b
    # offset clocks / newly_chosen / retire_mask — no extra protocol
    # work). Structurally OFF unless the serve loop sized the reservoir
    # (span_slots == 0 default: a trace-time no-op, like window=0).
    if telemetry_mod.span_slots(tel):
        p1_mark = jnp.zeros((G,), bool)
        if crash_on or cfg.device_elections:
            p1_mark = p1_mark | elect
        if cfg.reconfigure_every:
            p1_mark = p1_mark | p1_done
        if lc.reconfig:
            # Traced-epoch switches repair through the phase-1 plane:
            # the reconfiguration pause is a phase1_promised stamp on
            # every live span (visible in the Perfetto trace).
            p1_mark = p1_mark | sw_g
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=is_new,
            # Per-group slot number at each ring position (OLD head +
            # ordinal — valid for every cell occupied at tick start,
            # including the ones retiring this tick). Under window
            # rotation, the pre-roll rotation base makes the numbering
            # ABSOLUTE, so span ids stay stable across rolls (the
            # Python-level gate keeps the none-plan trace untouched).
            slot_ids=(
                lc_base + state.head[:, None] + ord_of_pos
                if lc.compaction
                else state.head[:, None] + ord_of_pos
            ),
            # Cells proposed THIS tick carry a slot one window past the
            # old-head formula when they were retired + re-proposed in
            # one tick: their numbering is OLD next_slot + ordinal.
            new_slot_ids=(
                lc_base
                + state.next_slot[:, None]
                + jnp.mod(w_iota[None, :] - state.next_slot[:, None], W)
                if lc.compaction
                else state.next_slot[:, None]
                + jnp.mod(w_iota[None, :] - state.next_slot[:, None], W)
            ),
            phase1_mark=p1_mark,
            # A Phase2b vote is visible at the counter: the same
            # offset-clock predicate check_invariants uses.
            voted=jnp.any(p2b_arrival <= 0, axis=0),
            newly_chosen=newly_chosen,
            retire_mask=retire_mask,
        )

    # ---- 8. Window rotation (tpu/lifecycle.py): the in-place roll.
    # When this tick's shift fired (a whole number of rotate_every
    # quanta, itself a multiple of the backend's alignment), every
    # absolute slot number and every slot-derived id rebases by the
    # shift — ring positions (slot mod W), client residues (mod NC),
    # and kv key residues (id mod KV) are all invariant under an
    # aligned shift, the offset clocks are already relative, and the
    # head-relative read deltas never move: the rebased run replays
    # the unrotated run bit for bit (the rotation-exactness pin). A
    # zero shift is the identity; the whole leg is absent at trace
    # time under LifecyclePlan.none().
    if lc.compaction:
        gshift = lc_shift * G  # the id/global-numbering shift

        def _rebase(args):
            # Historical tables (kv_val / ct_last): an id stale beyond
            # the margin (possible only through long noop-repair /
            # duplicate streaks) demotes to the unset sentinel.
            # Outcome-preserving: commands only ever carry RECENT ids
            # (fresh slots or client_last_issued re-issues, both
            # margin-protected), and any recent id beats a stale table
            # entry whether it reads as its true stale value or as -1
            # — the compact/ GC analog of a session record aging out
            # of the retained log.
            (hd, ns, sv, cv, vv, gw, kv, ctl, cli, mcg, cw, rs, rt,
             rf, lgw) = args
            return (
                lifecycle_mod.shift_counts(hd, lc_shift),
                lifecycle_mod.shift_counts(ns, lc_shift),
                lifecycle_mod.shift_ids(sv, gshift),
                lifecycle_mod.shift_ids(cv, gshift),
                lifecycle_mod.shift_ids(vv, gshift),
                lifecycle_mod.shift_ids(gw, lc_shift),
                lifecycle_mod.shift_ids(kv, gshift, floor=-1),
                lifecycle_mod.shift_ids(ctl, gshift, floor=-1),
                lifecycle_mod.shift_ids(cli, gshift),
                lifecycle_mod.shift_ids(mcg, gshift),
                lifecycle_mod.shift_ids(cw, gshift),
                lifecycle_mod.shift_ids(rs, gshift),
                lifecycle_mod.shift_ids(rt, gshift),
                lifecycle_mod.shift_ids(rf, gshift),
                lifecycle_mod.shift_ids(lgw, lc_shift),
            )

        # lax.cond: the rebase sweeps run ONLY on a tick whose shift
        # fired (one tick in a quantum) — every other tick pays a
        # branch, not len(fields) identity wheres over the slot planes
        # (the <2% overhead budget of bench.py --lifecycle).
        (
            head, next_slot, slot_value, chosen_value, vote_value,
            gc_watermark, kv_val, ct_last, client_last_issued,
            max_chosen_global, client_watermark, resp_slot, rb_target,
            rb_floor, lc_gcw,
        ) = jax.lax.cond(
            lc_shift > 0,
            _rebase,
            lambda args: args,
            (
                head, next_slot, slot_value, chosen_value, vote_value,
                gc_watermark, kv_val, ct_last, client_last_issued,
                max_chosen_global, client_watermark, resp_slot,
                rb_target, rb_floor,
                lcs.gc_watermark if lc.reconfig
                else jnp.zeros((0,), jnp.int32),
            ),
        )
        if lc.reconfig:
            lcs = dataclasses.replace(lcs, gc_watermark=lc_gcw)

    return BatchedMultiPaxosState(
        leader_round=leader_round,
        next_slot=next_slot,
        head=head,
        status=_pack_status(cfg, status),
        slot_value=slot_value,
        propose_tick=propose_tick,
        last_send=last_send,
        chosen_tick=chosen_tick,
        chosen_round=chosen_round,
        chosen_value=chosen_value,
        replica_arrival=replica_arrival,
        acc_round=new_acc_round,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        vote_round=vote_round,
        vote_value=vote_value,
        executed=executed,
        committed=committed,
        retired=retired_total,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        leader_alive=leader_alive,
        heartbeat_miss=heartbeat_miss,
        elections=elections,
        recon_phase=recon_phase,
        config_epoch=config_epoch,
        rc_round=rc_round,
        rc_epoch=rc_epoch,
        mm_epoch=mm_epoch,
        matcha_arrival=matcha_arrival,
        matchb_arrival=matchb_arrival,
        rc_p1a_arrival=rc_p1a,
        rc_p1b_arrival=rc_p1b,
        gc_watermark=gc_watermark,
        old_live=old_live,
        reconfigs=reconfigs,
        configs_gcd=configs_gcd,
        kv_val=kv_val,
        ct_last=ct_last,
        client_last_issued=client_last_issued,
        slot_is_dup=slot_is_dup,
        sm_applied=sm_applied,
        dups_filtered=dups_filtered,
        dups_seen=dups_seen,
        acc_max_slot=acc_max_slot,
        max_chosen_global=max_chosen_global,
        client_watermark=client_watermark,
        wave_issue=wave_issue,
        req_arrival=req_arrival,
        resp_slot=resp_slot,
        resp_arrival=resp_arrival,
        rb_status=_pack_status(cfg, rb_status),
        rb_count=rb_count,
        rb_wave=rb_wave,
        rb_issue=rb_issue,
        rb_target=rb_target,
        rb_floor=rb_floor,
        rb_reply_arrival=rb_reply_arrival,
        reads_done=reads_done,
        reads_shed=reads_shed,
        read_lat_sum=read_lat_sum,
        read_lat_hist=read_lat_hist,
        read_lin_violations=read_lin_violations,
        workload=wls,
        lifecycle=lcs,
        elastic=els,
        telemetry=tel,
    )


def leader_change(
    cfg: BatchedMultiPaxosConfig,
    state: BatchedMultiPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedMultiPaxosState:
    """Host-injected leader takeover (Leader.leaderChange + startPhase1,
    Leader.scala:409-459): bump every group's round and run phase-1 log
    repair via the registry's ``multipaxos_p1_promise`` plane with an
    all-acceptors oracle read (a superset of any f+1 read quorum). The
    device-side analog — failure injection, heartbeat-miss detection,
    and election — runs inside ``tick`` when ``cfg.fail_rate > 0``; this
    host API remains for tests and crafted cross-validation scenarios."""
    G, W, A = cfg.num_groups, cfg.window, cfg.group_size
    # Host writes land BETWEEN ticks: the at-rest offset clocks are
    # relative to tick t-1 (the next tick's aging rebases them), so an
    # arrival at t + lat stores lat + 1 — preserving the absolute-clock
    # arrival schedule exactly.
    lat = (
        sample_latency(cfg.lat_min, cfg.lat_max, key, (A, G, W)) + 1
    ).astype(state.p2a_arrival.dtype)
    slot_value, p2a_arrival, p2b_arrival, last_send = ops_registry.dispatch(
        "multipaxos_p1_promise",
        cfg,
        _unpack_status(cfg, state.status, W),
        state.vote_round,
        state.vote_value,
        state.slot_value,
        state.p2a_arrival,
        state.p2b_arrival,
        state.last_send,
        jnp.ones((G,), bool),
        jnp.ones((A, G), bool),
        lat,
        t,
    )
    return dataclasses.replace(
        state,
        leader_round=state.leader_round + 1,
        slot_value=slot_value,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        last_send=last_send,
    )


def reconfigure(
    cfg: BatchedMultiPaxosConfig,
    state: BatchedMultiPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedMultiPaxosState:
    """Matchmaker-style acceptor reconfiguration (BASELINE config 4; the
    batched analog of matchmakermultipaxos: the leader matchmakes a NEW
    acceptor configuration bound to the next round, phase-1s against the
    old configuration to learn its votes, adopts safe values, and
    re-proposes every in-flight slot to the new acceptors).

    Built on leader_change (round bump == configuration epoch bump +
    phase-1 repair reading every old acceptor, a superset of any read
    quorum). On top of it, the new configuration starts fresh: in-flight
    slots' vote state and pending Phase2bs clear (the new acceptors have
    never voted), and the acceptors arrive knowing the configuration's
    round (the matchmaker hands them the config bound to it). CHOSEN
    slots keep their old-configuration vote record until they retire —
    the analog of old configurations being garbage collected only once
    the chosen watermark passes them (Reconfigurer/GC pipeline)."""
    state = leader_change(cfg, state, t, key)  # also clears pending Phase2bs
    in_flight = (
        _unpack_status(cfg, state.status, cfg.window) == PROPOSED
    )[None, :, :]
    return dataclasses.replace(
        state,
        acc_round=jnp.broadcast_to(
            state.leader_round[None, :], state.acc_round.shape
        ),
        vote_round=jnp.where(in_flight, -1, state.vote_round),
        vote_value=jnp.where(in_flight, NO_VALUE, state.vote_value),
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedMultiPaxosConfig,
    state: BatchedMultiPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedMultiPaxosState, jnp.ndarray]:
    """Run ``num_ticks`` ticks under lax.scan; returns (state, t0+num_ticks).

    ``state`` is DONATED: its buffers alias the output state, so the
    whole cluster state is single-buffered in device memory across
    segments instead of double-buffered. Callers must not touch the
    passed-in state afterwards — rebind it (``state, t = run_ticks(cfg,
    state, ...)``) like every call site in the repo does."""

    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedMultiPaxosConfig, state: BatchedMultiPaxosState, t
) -> dict:
    """Device-side safety checks (the batched analog of the sim invariants).
    Returns a dict of boolean scalars; all must be True."""
    f = cfg.f
    # Packed storage: invariants read the unpacked (int8) view.
    status = _unpack_status(cfg, state.status, cfg.window)
    rb_status = _unpack_status(cfg, state.rb_status, cfg.read_window)
    chosen = status == CHOSEN
    # Chosen slots have a quorum of votes at (or, after a repair
    # re-proposal bumped vote_round, above) the round they were chosen in.
    # Offset clocks: "arrived" is offset <= 0 (INF16 = never).
    votes = (state.p2b_arrival <= 0) & (
        state.vote_round >= state.chosen_round[None, :, :]
    )
    quorum_ok = jnp.all(jnp.where(chosen, jnp.sum(votes, axis=0) >= f + 1, True))
    # Heads never pass next_slot; windows never overfill.
    window_ok = jnp.all(
        (state.head <= state.next_slot)
        & (state.next_slot - state.head <= cfg.window)
    )
    # Retired + in-flight bookkeeping is conserved.
    conserved = jnp.sum(state.executed) == state.retired
    # Acceptors never promised below the leader round they voted in.
    round_ok = jnp.all(
        state.acc_round[:, :, None] >= jnp.where(
            state.vote_round >= 0, state.vote_round, 0
        )
    )
    # Values: chosen slots carry a real value or a repair noop, never
    # unset; and every vote in the chosen round is for the chosen value
    # (one leader proposes one value per (round, slot)).
    value_set_ok = jnp.all(
        jnp.where(chosen, state.chosen_value != NO_VALUE, True)
    )
    vote_in_chosen_round = (
        chosen[None, :, :]
        & (state.vote_round == state.chosen_round[None, :, :])
    )
    vote_value_ok = jnp.all(
        jnp.where(
            vote_in_chosen_round,
            state.vote_value == state.chosen_value[None, :, :],
            True,
        )
    )
    # Reads: no read may bind below the chosen floor recorded at its issue
    # (read-quorum/write-quorum intersection — the linearizability
    # guarantee of the Evelyn read path); ring states stay in range.
    # Trivially true when reads are off (empty arrays).
    read_lin_ok = state.read_lin_violations == 0
    read_ring_ok = (
        jnp.all((rb_status >= R_EMPTY) & (rb_status <= R_SENT))
        # A batch carries reads iff it exists (count bookkeeping).
        & jnp.all((state.rb_count == 0) == (rb_status == R_EMPTY))
        & jnp.all(state.rb_count >= 0)
        # A waiting batch always references the wave it rides.
        & jnp.all(jnp.where(rb_status == R_WAIT, state.rb_wave >= 0, True))
    )
    # Global slot numbering (s*G + g) is int32: it overflows once any
    # group's head passes 2^31/G (~644k slots at G=3334), after which the
    # watermark comparison would silently stall reads. Fail LOUDLY here
    # instead — runs needing a longer horizon must rebase the numbering.
    slot_horizon_ok = jnp.max(state.head) < jnp.int32(0x7FFFFFFF) // jnp.int32(
        max(cfg.num_groups, 1)
    )
    # Outside an in-flight reconfiguration, no acceptor is promised past
    # the leader round — an over-promise (e.g. a straggler Phase1a
    # processed with a post-bump round) would silently lock the acceptor
    # out of voting until the next round bump (Acceptor.scala
    # handlePhase2a's round check). During RC_PHASE1 acceptors are
    # legitimately one round ahead (they promised the incoming round).
    rc_promise_ok = jnp.all(
        state.acc_round
        <= state.leader_round[None, :]
        + (state.recon_phase != RC_NORMAL).astype(jnp.int32)[None, :]
    )
    # Matchmaker bookkeeping: phases stay in range, every live old config
    # has an armed GC watermark, and per-group epochs sum to the global
    # reconfiguration counter. Trivially true when the feature is off.
    recon_ok = jnp.all(
        (state.recon_phase >= RC_NORMAL) & (state.recon_phase <= RC_PHASE1)
    )
    rc_books_ok = (jnp.sum(state.config_epoch) == state.reconfigs) & jnp.all(
        ~state.old_live | (state.gc_watermark >= 0)
    )
    # Matchmakers record epochs monotonically, never ahead of the one
    # reconfiguration that may be in flight; once a group is back in
    # RC_NORMAL, an f+1 matchmaker quorum knows its current epoch (the
    # Matchmaker.scala:handleMatchA guarantee that lets the NEXT
    # reconfigurer learn the configuration).
    mm_ok = jnp.all(
        state.mm_epoch <= state.config_epoch[None, :] + 1
    ) & jnp.all(
        jnp.where(
            state.recon_phase == RC_NORMAL,
            jnp.sum(state.mm_epoch >= state.config_epoch[None, :], axis=0)
            >= jnp.where(state.config_epoch > 0, f + 1, 0),
            True,
        )
    )
    # State machine + client table (trivially true when the SM is off —
    # zero-width arrays, zero counters). Exactly-once: only re-issued ids
    # are ever filtered (a fresh command always executes), so filtered <=
    # flagged; equality holds in noop-free runs, but a failover can
    # repair a dup's ORIGINAL slot to a noop (Leader.scala:541-575), in
    # which case the retry legitimately executes — that is exactly-once
    # working as intended, not a missed dedup (the host-replay test pins
    # the exact decision per command). Residency: stored ids belong to
    # the right group/key/client; and no client ever executes an id it
    # never issued.
    sm_dedup_ok = state.dups_filtered <= state.dups_seen
    G_ = max(cfg.num_groups, 1)
    g_col = jnp.arange(cfg.num_groups, dtype=jnp.int32)[:, None]
    k_row = jnp.arange(state.kv_val.shape[1], dtype=jnp.int32)[None, :]
    kv_ok = jnp.all(
        jnp.where(
            state.kv_val >= 0,
            (state.kv_val % max(cfg.kv_keys, 1) == k_row)
            & (state.kv_val % G_ == g_col),
            True,
        )
    )
    c_row = jnp.arange(state.ct_last.shape[1], dtype=jnp.int32)[None, :]
    ct_ok = (
        jnp.all(
            jnp.where(
                state.ct_last >= 0,
                ((state.ct_last // G_) % max(cfg.num_clients, 1) == c_row)
                & (state.ct_last % G_ == g_col),
                True,
            )
        )
        & jnp.all(state.ct_last <= state.client_last_issued)
    )
    return {
        "quorum_ok": quorum_ok,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        # Lifecycle books: session ids conserved against the lane's
        # completion counts (and, when the workload engine is also
        # active, against ITS completion totals — exactly-once
        # accounting and window conservation are the same books),
        # rotation counters monotone, reconfiguration GC armed.
        "lifecycle_ok": lifecycle_mod.invariants_ok(
            cfg.lifecycle,
            state.lifecycle,
            workload_completed=(
                state.workload.completed
                if cfg.lifecycle.has_sessions and cfg.workload.active
                else None
            ),
        ),
        # Elastic books: active/target counts inside [floor, capacity],
        # resize generation and event counters monotone.
        "elastic_ok": elastic_mod.invariants_ok(
            cfg.elastic, state.elastic
        ),
        "window_ok": window_ok,
        "conserved": conserved,
        "round_ok": round_ok,
        "value_set_ok": value_set_ok,
        "vote_value_ok": vote_value_ok,
        "read_lin_ok": read_lin_ok,
        "read_ring_ok": read_ring_ok,
        "sm_dedup_ok": sm_dedup_ok,
        "kv_ok": kv_ok,
        "ct_ok": ct_ok,
        "slot_horizon_ok": slot_horizon_ok,
        "recon_ok": recon_ok,
        "rc_promise_ok": rc_promise_ok,
        "rc_books_ok": rc_books_ok,
        "mm_ok": mm_ok,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
    lifecycle: LifecyclePlan = LifecyclePlan.none(),
    elastic: ElasticPlan = ElasticPlan.none(),
) -> BatchedMultiPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    if elastic.active and not workload.shaped:
        # The elastic 'groups' role routes ARRIVALS: an elastic
        # analysis config needs an open-loop shaped workload.
        workload = WorkloadPlan(arrival="constant", rate=2.0)
    return BatchedMultiPaxosConfig(
        f=1, num_groups=4, window=16, slots_per_tick=2,
        retry_timeout=8, faults=faults, workload=workload,
        lifecycle=lifecycle, elastic=elastic,
    )
