"""Crash-tolerant checkpoint/restore for the batched backends: async
alias-free State snapshots, versioned on-disk checkpoints with
torn-write defense, and bit-exact restore.

The serve loop (``harness/serve.py``) can now run forever in-graph —
window rotation keeps the slot horizon constant, the session table
gives exactly-once semantics, and ``FaultPlan`` injects every
device-side failure — but the HOST process driving the loop was still a
single point of failure: a preemption, OOM, or SIGKILL lost the whole
run. This module closes that: because every piece of protocol,
workload, telemetry, and lifecycle state — including the counter-based
PRNG position and the drain cursors — lives in one donated State
pytree, a checkpoint of that pytree plus a small host-context manifest
is sufficient to resume a run BIT-EXACTLY: the resumed run replays the
uninterrupted twin sha256-identically (a stronger guarantee than the
reference's TCP reconnect story, and pinned the same way every prior
subsystem is — by digest twins in ``tests/test_checkpoint.py``).

Three layers:

  * **Async snapshot** — :func:`snapshot_tree` is a jitted, ALIAS-FREE
    device-side copy of the full State (+ tick scalar). The serve loop
    enqueues it right behind a chunk's ``run_ticks`` and drains it to
    disk while the NEXT chunk computes — the same double-buffer
    discipline as the telemetry drain: the copy is what makes the
    buffers survive the next chunk's donation, and the loop never adds
    a ``block_until_ready``. The ``checkpoint-alias-free`` analysis
    rule pins that the compiled snapshot program aliases no input (an
    aliased output would be reused by the donation while the disk
    write still reads it) and smuggles no host callback.
  * **Versioned on-disk format** — one checkpoint is a pair
    ``ckpt_<step>.npz`` (flat leaf arrays, keys = dotted State paths)
    + ``ckpt_<step>.json`` (the manifest: format version, config
    fingerprint, tick count, DrainCursor position, host context, and
    per-leaf CRC32 checksums + shapes + dtypes). Both are written to a
    temp name and atomically renamed, ARRAYS FIRST: the manifest is
    the commit point, so a crash mid-write leaves either a complete
    checkpoint or a torn one the loader rejects.
  * **Torn/corrupt-snapshot defense** — :func:`load_checkpoint`
    verifies the format version, every leaf's presence, shape, dtype,
    and checksum, and the manifest's own structure;
    :func:`latest_valid` walks checkpoints newest-first and returns
    the first that fully verifies, so a torn or bit-flipped newest
    checkpoint falls back to the previous valid one (corruption
    injection is tested: truncated npz, flipped bytes, missing
    manifest, stale config hash).

Restore (:func:`restore_leaves`) rebuilds the State onto a freshly
constructed template with EXACT dtypes and shapes, so the first
``run_ticks`` after a same-process restore hits the existing jit cache
— no recompile (pinned by the ``trace-checkpoint-restore`` analysis
rule); across a process restart the one cold-start compile is the only
compile.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

# Bumped whenever the on-disk layout changes; a manifest carrying a
# different version is rejected (stale-format defense).
CHECKPOINT_FORMAT = 1

_CKPT_RE = re.compile(r"^ckpt_(\d{8})\.json$")


class CheckpointError(RuntimeError):
    """A checkpoint failed to load or verify (torn write, corrupt leaf,
    stale manifest, wrong config). ``latest_valid`` catches these and
    falls back; explicit loads surface them."""


# ---------------------------------------------------------------------------
# Device side: the async alias-free snapshot
# ---------------------------------------------------------------------------


def _copy_tree(tree):
    """Outputs are FRESH buffers (inputs are not donated, so XLA must
    materialize copies) — the disk drain can read them after the next
    chunk donates the state they were copied from."""
    return jax.tree_util.tree_map(jnp.copy, tree)


_SNAP = jax.jit(_copy_tree)


def snapshot_tree(tree):
    """Enqueue a jitted alias-free device-side copy of ``tree`` (the
    full State + tick scalar). Returns a pytree of futures — NO
    blocking call happens here; ``jax.device_get`` it after dispatching
    the next chunk."""
    return _SNAP(tree)


def lower_snapshot(tree):
    """Lower the snapshot program for inspection — used by the
    ``checkpoint-alias-free`` analysis rule so the rule checks exactly
    the program the serve loop runs."""
    return _SNAP.lower(tree)


# ---------------------------------------------------------------------------
# Naming, fingerprints, digests
# ---------------------------------------------------------------------------


def config_fingerprint(mod, cfg) -> str:
    """A stable fingerprint of (backend, config): restoring a
    checkpoint under a DIFFERENT config would silently mis-shape the
    run, so the manifest carries this and resume rejects a mismatch
    (the stale-manifest defense). Frozen dataclass reprs are
    deterministic and cover every nested plan."""
    text = f"{getattr(mod, '__name__', mod)}|{cfg!r}"
    return hashlib.sha256(text.encode()).hexdigest()


def flatten_state(state) -> Dict[str, Any]:
    """The State pytree as an ordered ``{dotted-path: leaf}`` dict —
    the npz key schema. Paths come from the registered-dataclass field
    names, so they are stable across processes."""
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    out: Dict[str, Any] = {}
    for path, leaf in flat:
        name = ".".join(
            str(getattr(p, "name", getattr(p, "key", getattr(p, "idx", p))))
            for p in path
        ) or "_root"
        assert name not in out, f"duplicate leaf path {name}"
        out[name] = leaf
    return out


def state_digest(state) -> str:
    """sha256 over every leaf's path, dtype, shape, and raw bytes — the
    twin-comparison digest the resume==uninterrupted tests pin. One
    coalesced ``device_get``."""
    import numpy as np

    host = jax.device_get(state)
    h = hashlib.sha256()
    for name, leaf in sorted(flatten_state(host).items()):
        arr = np.asarray(leaf)
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _names(step: int) -> Tuple[str, str]:
    return f"ckpt_{step:08d}.npz", f"ckpt_{step:08d}.json"


# ---------------------------------------------------------------------------
# Writing
# ---------------------------------------------------------------------------


def _atomic_write(path: str, write_fn) -> None:
    """Write-to-temp-then-rename in the target directory (same
    filesystem, so the rename is atomic): a crash mid-write leaves a
    ``.tmp`` orphan, never a half-written checkpoint under the real
    name."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_checkpoint(
    ckpt_dir: str,
    *,
    leaves: Dict[str, Any],
    meta: Dict[str, Any],
    step: int,
    keep: int = 0,
) -> str:
    """Write one versioned checkpoint: the flat leaf arrays as an npz,
    then the manifest (format version + ``meta`` + per-leaf CRC32
    checksums/shapes/dtypes). Arrays first, manifest last — the
    manifest rename is the commit point. ``keep > 0`` prunes all but
    the newest ``keep`` checkpoints afterwards (never the one just
    written). Returns the manifest path."""
    import numpy as np

    os.makedirs(ckpt_dir, exist_ok=True)
    arrays = {name: np.asarray(leaf) for name, leaf in leaves.items()}
    npz_name, man_name = _names(step)

    def write_npz(f):
        np.savez(f, **arrays)

    _atomic_write(os.path.join(ckpt_dir, npz_name), write_npz)

    manifest = {
        "format": CHECKPOINT_FORMAT,
        "step": int(step),
        "arrays_file": npz_name,
        "leaves": {
            name: {
                "crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF,
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
            }
            for name, arr in arrays.items()
        },
        **meta,
    }
    payload = json.dumps(manifest, indent=1).encode()

    def write_man(f):
        f.write(payload)

    man_path = os.path.join(ckpt_dir, man_name)
    _atomic_write(man_path, write_man)
    if keep > 0:
        prune(ckpt_dir, keep=keep)
    return man_path


def prune(ckpt_dir: str, keep: int) -> List[int]:
    """Remove all but the newest ``keep`` checkpoints (by step);
    returns the pruned steps. Orphan ``.tmp`` files are swept too."""
    steps = sorted(list_steps(ckpt_dir))
    pruned = steps[:-keep] if keep > 0 else []
    for step in pruned:
        for name in _names(step):
            try:
                os.unlink(os.path.join(ckpt_dir, name))
            except OSError:
                pass
    for fn in os.listdir(ckpt_dir):
        if fn.endswith(".tmp"):
            try:
                os.unlink(os.path.join(ckpt_dir, fn))
            except OSError:
                pass
    return pruned


def list_steps(ckpt_dir: str) -> List[int]:
    """Steps that have a COMMITTED manifest (arrays may still be torn —
    the loader verifies)."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for fn in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(fn)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


# ---------------------------------------------------------------------------
# Loading + verification (the torn/corrupt-snapshot defense)
# ---------------------------------------------------------------------------


def load_checkpoint(
    ckpt_dir: str, step: int
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load + fully verify one checkpoint; returns
    ``(manifest, arrays)``. Raises :class:`CheckpointError` on ANY
    defect: unreadable/structurally-wrong manifest, format-version
    mismatch, missing arrays file, missing/extra leaves, shape or
    dtype drift, or a checksum mismatch (torn or bit-flipped write)."""
    import numpy as np

    _, man_name = _names(step)
    man_path = os.path.join(ckpt_dir, man_name)
    try:
        with open(man_path, "rb") as f:
            manifest = json.loads(f.read().decode())
    except (OSError, ValueError, UnicodeDecodeError) as e:
        raise CheckpointError(f"unreadable manifest {man_path}: {e}")
    if not isinstance(manifest, dict) or "leaves" not in manifest:
        raise CheckpointError(f"malformed manifest {man_path}")
    if manifest.get("format") != CHECKPOINT_FORMAT:
        raise CheckpointError(
            f"{man_path}: format {manifest.get('format')} != "
            f"{CHECKPOINT_FORMAT}"
        )
    npz_path = os.path.join(
        ckpt_dir, manifest.get("arrays_file", _names(step)[0])
    )
    try:
        with np.load(npz_path) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as e:  # noqa: BLE001 — any read failure IS the
        # defect this loader defends against (torn zip members raise
        # zipfile.BadZipFile, truncated streams EOFError/OSError,
        # garbage ValueError — all mean: reject, fall back).
        raise CheckpointError(f"unreadable arrays {npz_path}: {e}")
    want = manifest["leaves"]
    missing = sorted(set(want) - set(arrays))
    extra = sorted(set(arrays) - set(want))
    if missing or extra:
        raise CheckpointError(
            f"{npz_path}: leaf set mismatch (missing {missing[:4]}, "
            f"extra {extra[:4]})"
        )
    for name, spec in want.items():
        arr = arrays[name]
        if str(arr.dtype) != spec["dtype"] or list(arr.shape) != list(
            spec["shape"]
        ):
            raise CheckpointError(
                f"{npz_path}:{name}: dtype/shape drift "
                f"({arr.dtype}{arr.shape} != "
                f"{spec['dtype']}{tuple(spec['shape'])})"
            )
        crc = zlib.crc32(np.asarray(arr).tobytes()) & 0xFFFFFFFF
        if crc != spec["crc32"]:
            raise CheckpointError(
                f"{npz_path}:{name}: checksum mismatch (torn or "
                "corrupt write)"
            )
    return manifest, arrays


def latest_valid(
    ckpt_dir: str, config_hash: Optional[str] = None
) -> Optional[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """The newest checkpoint that fully verifies (and, when
    ``config_hash`` is given, matches it) — the automatic-fallback
    entry point: a torn/corrupt/stale newest checkpoint is skipped and
    the previous valid one restores instead. Returns None when no
    valid checkpoint exists. Skipped defects are recorded on the
    returned manifest under ``"skipped"``."""
    skipped: List[str] = []
    for step in reversed(list_steps(ckpt_dir)):
        try:
            manifest, arrays = load_checkpoint(ckpt_dir, step)
        except CheckpointError as e:
            skipped.append(str(e))
            continue
        if config_hash is not None and manifest.get("config_hash") != (
            config_hash
        ):
            skipped.append(
                f"step {step}: config fingerprint mismatch (stale "
                "manifest — checkpoint belongs to a different config)"
            )
            continue
        if skipped:
            manifest = dict(manifest, skipped=skipped)
        return manifest, arrays
    return None


# ---------------------------------------------------------------------------
# Restore
# ---------------------------------------------------------------------------


def restore_leaves(template_state, arrays: Dict[str, Any]):
    """Rebuild a State pytree from flat checkpoint arrays onto a
    template (a freshly constructed ``init_state`` with the same
    config + telemetry sizing): every template leaf must be present
    with the exact shape and dtype, and the restored leaves are
    committed device arrays with the template's dtypes — so the first
    ``run_ticks`` after a same-process restore HITS the existing jit
    cache (no recompile; the ``trace-checkpoint-restore`` rule pins
    this)."""
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten_with_path(template_state)
    names = list(flatten_state(template_state))
    assert len(names) == len(flat)
    leaves = []
    for name, (path, tmpl) in zip(names, flat):
        if name not in arrays:
            raise CheckpointError(f"restore: leaf {name} missing")
        arr = arrays[name]
        t_dtype = jnp.asarray(tmpl).dtype
        if tuple(arr.shape) != tuple(jnp.shape(tmpl)):
            raise CheckpointError(
                f"restore: {name} shape {tuple(arr.shape)} != template "
                f"{tuple(jnp.shape(tmpl))} (config drift?)"
            )
        if str(arr.dtype) != str(t_dtype):
            raise CheckpointError(
                f"restore: {name} dtype {arr.dtype} != template "
                f"{t_dtype} (dtype-policy drift?)"
            )
        # An XLA-OWNED copy — never bare jnp.asarray/device_put: on the
        # CPU backend those can alias the host numpy buffer zero-copy,
        # and the first donated run_ticks would then hand XLA memory it
        # doesn't own (observed as glibc heap corruption under the
        # warm-compile-cache timing). jnp.copy stages a real device
        # copy whose output buffer XLA allocates itself.
        leaves.append(jnp.copy(jnp.asarray(np.asarray(arr), t_dtype)))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Convenience: whole-state save/restore (the simtest + analysis-rule
# entry points; the serve loop drives the pieces directly for the
# async overlap).
# ---------------------------------------------------------------------------


def save_state(
    ckpt_dir: str,
    mod,
    cfg,
    state,
    t,
    *,
    step: int,
    extra_meta: Optional[Dict[str, Any]] = None,
    keep: int = 0,
) -> str:
    """One-call synchronous checkpoint of (state, t): snapshot, pull,
    write. The serve loop instead splits these steps around the next
    chunk's dispatch (the async path); this form serves the harnesses
    and the analysis rules."""
    host = jax.device_get(snapshot_tree({"state": state, "t": t}))
    leaves = flatten_state(host["state"])
    leaves["__t__"] = host["t"]
    meta = {
        "config_hash": config_fingerprint(mod, cfg),
        "backend": getattr(mod, "__name__", str(mod)).rsplit(".", 1)[-1],
        "tick": int(host["t"]),
    }
    if extra_meta:
        meta.update(extra_meta)
    return save_checkpoint(
        ckpt_dir, leaves=leaves, meta=meta, step=step, keep=keep
    )


def restore_state(ckpt_dir: str, mod, cfg, template_state):
    """Restore the newest valid checkpoint matching (mod, cfg):
    returns ``(state, t, manifest)``. Raises :class:`CheckpointError`
    when no valid checkpoint exists."""
    found = latest_valid(
        ckpt_dir, config_hash=config_fingerprint(mod, cfg)
    )
    if found is None:
        raise CheckpointError(
            f"no valid checkpoint for this config under {ckpt_dir}"
        )
    manifest, arrays = found
    t = jnp.asarray(arrays.pop("__t__"), jnp.int32)
    state = restore_leaves(template_state, arrays)
    return state, t, manifest


# ---------------------------------------------------------------------------
# Host-context serialization helpers (numpy arrays <-> JSON lists)
# ---------------------------------------------------------------------------


def jsonable(obj):
    """Recursively convert numpy scalars/arrays (and dataclasses) into
    JSON-serializable values — the manifest's host-context fields."""
    import numpy as np

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return jsonable(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if hasattr(obj, "item") and not isinstance(obj, (str, bytes)):
        try:
            return obj.item()
        except Exception:
            pass
    return obj
