"""Batched Vanilla Mencius as a single XLA program: the REVOCATION
mechanic (reference ``vanillamencius/Server.scala`` — a live server
revokes a dead peer's owned slots by running full Paxos at a higher
round on them; per-actor analog ``protocols/vanillamencius.py``).

Mencius stripes one global log round-robin over ``L`` servers. Plain
Mencius lets a LIVE laggard noop-fill its own stripe (skips,
``mencius_batched.py``); Vanilla Mencius's defining extra is what
happens when the owner is DEAD: it cannot skip, its stripe pins the
global execution watermark, and a live peer must take the owner's slots
away — phase 1 at round 1 against the stripe's acceptor group, then
phase 2 proposing the SAFE value (the owner's value if phase 1 reveals
a round-0 vote — the owner may have gotten a quorum before dying — else
a noop). A promise at round 1 makes acceptors reject the dead owner's
straggling round-0 Phase2as, which is the safety teeth of the
mechanism.

TPU-first layout mirrors ``mencius_batched.py``: [L] stripes, [L, W]
owned-slot rings, [L, W, A] per-acceptor arrays, global watermark =
min over stripes of (contiguous prefix * L + l). Revocation state rides
the same ring (rv_phase/rv_value + phase-1/2 message arrays). The
choose-once ledger counts any slot chosen twice with different values —
the invariant revocation must preserve.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_ROUND,
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_delivered,
    bit_latency,
    ring_retire,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

EMPTY = 0
PROPOSED = 1  # owner's round-0 proposal in flight
CHOSEN = 2

# Revocation phase (independent of status: revocation may target both
# EMPTY owned slots — claimed fresh — and PROPOSED-but-unchosen ones).
RV_NONE = 0
RV_P1 = 1  # round-1 Phase1a in flight
RV_P2 = 2  # round-1 Phase2a in flight

NO_VALUE = -1
NOOP_VALUE = -2


@dataclasses.dataclass(frozen=True)
class BatchedVanillaMenciusConfig:
    """Static simulation parameters. Each stripe has its own
    2f+1-acceptor group; servers die/revive by PRNG."""

    f: int = 1
    num_servers: int = 4  # L: stripes of the global log
    window: int = 32  # W: in-flight owned slots per stripe
    slots_per_tick: int = 2  # K: proposals per LIVE server per tick
    lat_min: int = 1
    lat_max: int = 3
    drop_rate: float = 0.0
    retry_timeout: int = 16
    fail_rate: float = 0.0  # per-server per-tick death probability
    revive_rate: float = 0.05
    # A dead stripe lagging the fastest frontier by more than this many
    # owned slots gets revoked by a live peer (Server.scala revocation).
    revoke_threshold: int = 8
    revoke_slots_per_tick: int = 8  # revocation batch per stripe per tick
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + an acceptor-axis partition on the shared
    # delivered plane (UDP semantics); crash/revive merges into the
    # native server fail/revive machinery — which is exactly what
    # drives revocation. FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes each LIVE
    # owner's per-tick proposal admission; revocation noops stay
    # protocol traffic. WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def group_size(self) -> int:
        return 2 * self.f + 1

    def __post_init__(self):
        assert self.f >= 1
        assert self.num_servers >= 2
        assert self.window >= 2 * self.slots_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.drop_rate < 1.0
        assert 0.0 <= self.fail_rate < 1.0
        assert 0.0 <= self.revive_rate <= 1.0
        assert self.revoke_threshold >= 1
        assert self.revoke_slots_per_tick >= 1
        self.faults.validate(axis=self.group_size)
        self.workload.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedVanillaMenciusState:
    """Shapes: [L] stripes, [L, W] owned-slot rings, [L, W, A] votes."""

    next_slot: jnp.ndarray  # [L] next OWNED ordinal (global = o*L + l)
    head: jnp.ndarray  # [L] lowest non-retired owned ordinal

    status: jnp.ndarray  # [L, W]
    slot_value: jnp.ndarray  # [L, W] proposed/chosen value (NO/NOOP)
    propose_tick: jnp.ndarray  # [L, W]
    last_send: jnp.ndarray  # [L, W]
    replica_arrival: jnp.ndarray  # [L, W]
    chosen_value: jnp.ndarray  # [L, W] value actually chosen (ledger)
    committed_prefix: jnp.ndarray  # [L]

    # Acceptors (per slot): promised round + round-0 vote state.
    acc_round: jnp.ndarray  # [L, W, A] 0 = owner round, 1 = revoked
    voted: jnp.ndarray  # [L, W, A] voted in round 0 (owner value)
    voted_r1: jnp.ndarray  # [L, W, A] voted in round 1 (rv_value)
    p2a_arrival: jnp.ndarray  # [L, W, A] owner round-0 Phase2a
    p2b_arrival: jnp.ndarray  # [L, W, A] round-0 Phase2b to owner

    # Revocation machinery (round 1).
    alive: jnp.ndarray  # [L] server liveness
    rv_phase: jnp.ndarray  # [L, W] RV_*
    rv_value: jnp.ndarray  # [L, W] value round 1 proposes (after p1)
    rv_p1a_arrival: jnp.ndarray  # [L, W, A]
    rv_p1b_arrival: jnp.ndarray  # [L, W, A]
    rv_p1b_voted: jnp.ndarray  # [L, W, A] p1b reports a round-0 vote
    rv_p2a_arrival: jnp.ndarray  # [L, W, A]
    rv_p2b_arrival: jnp.ndarray  # [L, W, A]

    executed_global: jnp.ndarray  # []
    committed: jnp.ndarray  # [] chosen slots (all)
    committed_real: jnp.ndarray  # [] chosen real commands
    revocations: jnp.ndarray  # [] slots revocation claimed
    revoked_discovered: jnp.ndarray  # [] revocations that found a vote
    deaths: jnp.ndarray  # []
    choose_violations: jnp.ndarray  # [] slot re-chosen with a new value
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(
    cfg: BatchedVanillaMenciusConfig,
) -> BatchedVanillaMenciusState:
    L, W, A = cfg.num_servers, cfg.window, cfg.group_size
    return BatchedVanillaMenciusState(
        next_slot=jnp.zeros((L,), jnp.int32),
        head=jnp.zeros((L,), jnp.int32),
        status=jnp.zeros((L, W), DTYPE_STATUS),
        slot_value=jnp.full((L, W), NO_VALUE, jnp.int32),
        propose_tick=jnp.full((L, W), INF, jnp.int32),
        last_send=jnp.full((L, W), INF, jnp.int32),
        replica_arrival=jnp.full((L, W), INF, jnp.int32),
        chosen_value=jnp.full((L, W), NO_VALUE, jnp.int32),
        committed_prefix=jnp.zeros((L,), jnp.int32),
        acc_round=jnp.zeros((L, W, A), DTYPE_ROUND),
        voted=jnp.zeros((L, W, A), bool),
        voted_r1=jnp.zeros((L, W, A), bool),
        p2a_arrival=jnp.full((L, W, A), INF, jnp.int32),
        p2b_arrival=jnp.full((L, W, A), INF, jnp.int32),
        alive=jnp.ones((L,), bool),
        rv_phase=jnp.zeros((L, W), DTYPE_STATUS),
        rv_value=jnp.full((L, W), NO_VALUE, jnp.int32),
        rv_p1a_arrival=jnp.full((L, W, A), INF, jnp.int32),
        rv_p1b_arrival=jnp.full((L, W, A), INF, jnp.int32),
        rv_p1b_voted=jnp.zeros((L, W, A), bool),
        rv_p2a_arrival=jnp.full((L, W, A), INF, jnp.int32),
        rv_p2b_arrival=jnp.full((L, W, A), INF, jnp.int32),
        executed_global=jnp.zeros((), jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        committed_real=jnp.zeros((), jnp.int32),
        revocations=jnp.zeros((), jnp.int32),
        revoked_discovered=jnp.zeros((), jnp.int32),
        deaths=jnp.zeros((), jnp.int32),
        choose_violations=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(cfg.workload, L, cfg.faults),
        telemetry=make_telemetry(),
    )


def _owner_value(ord_, l, L):
    return (ord_ * L + l) & jnp.int32(0x7FFFFFFF)


def tick(
    cfg: BatchedVanillaMenciusConfig,
    state: BatchedVanillaMenciusState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedVanillaMenciusState:
    L, W, A = cfg.num_servers, cfg.window, cfg.group_size
    f = cfg.f
    w_iota = jnp.arange(W, dtype=jnp.int32)
    stripe_ids = jnp.arange(L, dtype=jnp.int32)

    k3, k2, k1 = jax.random.split(key, 3)
    bits3 = jax.random.bits(k3, (L, W, A))  # [0:8) p2a/p1a lat,
    #                      [8:16) p2b/p1b lat, [16:24) rv lat, [24:32) drop
    bits2 = jax.random.bits(k2, (L, W))  # [0:8) replica lat
    bits1 = jax.random.bits(k1, (L,))  # [0:8) fail, [8:16) revive
    fwd_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    bwd_lat = bit_latency(bits3, 8, cfg.lat_min, cfg.lat_max)
    rv_lat = bit_latency(bits3, 16, cfg.lat_min, cfg.lat_max)
    rep_lat = bit_latency(bits2, 0, cfg.lat_min, cfg.lat_max)
    delivered = bit_delivered(bits3, 24, cfg.drop_rate)

    # Unified fault injection (tpu/faults.py): the plan folds into the
    # shared delivered plane and the revocation-round latency; crash
    # merges into the native server churn below. none() skips all of it.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    rv_delivered = delivered  # revocation-plane delivery (same native draw)
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, A)[None, None, :]
        f_del, fwd_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (L, W, A), fwd_lat, link_up,
            rates=frates,
        )
        f_del2, rv_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (L, W, A), rv_lat, link_up,
            rates=frates,
        )
        delivered = delivered & f_del
        rv_delivered = rv_delivered & f_del2

    status = state.status
    chosen_value = state.chosen_value

    # ---- 0. Liveness churn (Server failure model; ~bit_delivered(x, p)
    # is True with probability p — the guarded 8-bit Bernoulli). A
    # FaultPlan crash schedule composes with the native rates.
    eff_fail, eff_revive = faults_mod.effective_process_rates(
        fp, cfg.fail_rate, cfg.revive_rate, rates=frates
    )
    die = state.alive & ~bit_delivered(bits1, 0, eff_fail)
    revive = ~state.alive & ~bit_delivered(bits1, 8, eff_revive)
    alive = (state.alive & ~die) | revive
    deaths = state.deaths + jnp.sum(die)

    # ---- 1. Acceptors. Round-0 Phase2as (owner) vote ONLY if the
    # acceptor has not promised round 1 (the revocation promise rejects
    # the dead owner's stragglers — Acceptor round check).
    p2a_now = state.p2a_arrival == t
    vote0 = p2a_now & (state.acc_round == 0)
    voted = state.voted | vote0
    p2b_arrival = jnp.where(vote0, t + bwd_lat, state.p2b_arrival)
    p2a_arrival = jnp.where(p2a_now, INF, state.p2a_arrival)

    # Round-1 Phase1as: promise round 1, report any round-0 vote.
    p1a_now = state.rv_p1a_arrival == t
    acc_round = jnp.where(p1a_now, 1, state.acc_round)
    rv_p1b_voted = jnp.where(p1a_now, voted, state.rv_p1b_voted)
    rv_p1b_arrival = jnp.where(p1a_now, t + bwd_lat, state.rv_p1b_arrival)
    rv_p1a_arrival = jnp.where(p1a_now, INF, state.rv_p1a_arrival)

    # Round-1 Phase2as: vote (acc_round is already 1 — only sent after
    # the p1 quorum; a higher-round message also bumps the promise).
    rv_p2a_now = state.rv_p2a_arrival == t
    acc_round = jnp.where(rv_p2a_now, 1, acc_round)
    voted_r1 = state.voted_r1 | rv_p2a_now
    rv_p2b_arrival = jnp.where(rv_p2a_now, t + bwd_lat, state.rv_p2b_arrival)
    rv_p2a_arrival = jnp.where(rv_p2a_now, INF, state.rv_p2a_arrival)

    # ---- 2. Choose. Round 0: f+1 round-0 Phase2bs at the owner. The
    # owner must be ALIVE to count them (a dead owner learns nothing);
    # the votes still exist at the acceptors — which is exactly what
    # revocation's phase 1 must discover.
    n0 = jnp.sum((p2b_arrival <= t) & voted, axis=2)
    chosen0 = (
        (status == PROPOSED)
        & alive[:, None]
        & (state.rv_phase == RV_NONE)
        & (n0 >= f + 1)
    )
    # Round 1: f+1 round-1 Phase2bs at the revoker.
    n1 = jnp.sum((rv_p2b_arrival <= t) & voted_r1, axis=2)
    chosen1 = (state.rv_phase == RV_P2) & (n1 >= f + 1) & (status != CHOSEN)
    newly_chosen = chosen0 | chosen1
    value_now = jnp.where(chosen1, state.rv_value, state.slot_value)
    # Choose-once ledger: a slot re-chosen with a DIFFERENT value is a
    # safety violation (revocation must have discovered the round-0
    # choice).
    choose_violations = state.choose_violations + jnp.sum(
        newly_chosen
        & (chosen_value != NO_VALUE)
        & (chosen_value != value_now)
    )
    chosen_value = jnp.where(
        newly_chosen & (chosen_value == NO_VALUE), value_now, chosen_value
    )
    slot_value = jnp.where(chosen1, state.rv_value, state.slot_value)
    status = jnp.where(newly_chosen, CHOSEN, status)
    replica_arrival = jnp.where(
        newly_chosen, t + rep_lat, state.replica_arrival
    )
    rv_phase = jnp.where(chosen1, RV_NONE, state.rv_phase)

    real_chosen = newly_chosen & (slot_value != NOOP_VALUE)
    # Workload completions: an ADMITTED (real-valued owner) slot is
    # resolved when it gets chosen — even if revocation chose a noop
    # over it (the client observes the failure; the window must drain).
    if wl.active:
        wl_done = jnp.sum(
            newly_chosen & (state.slot_value != NOOP_VALUE), axis=1
        )
    latency = jnp.where(real_chosen, t - state.propose_tick, 0)
    committed = state.committed + jnp.sum(newly_chosen)
    committed_real = state.committed_real + jnp.sum(real_chosen)
    lat_sum = state.lat_sum + jnp.sum(latency)
    bins = jnp.clip(latency, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        real_chosen.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )

    # ---- 3. Revocation progress: a phase-1 quorum binds rv_value (the
    # discovered owner value if ANY reported round-0 vote, else noop)
    # and launches round-1 Phase2as.
    p1_in = jnp.sum(rv_p1b_arrival <= t, axis=2)
    p1_done = (state.rv_phase == RV_P1) & (p1_in >= f + 1)
    any_vote = jnp.any((rv_p1b_arrival <= t) & rv_p1b_voted, axis=2)
    ord_of_pos = state.head[:, None] + jnp.mod(
        w_iota[None, :] - state.head[:, None], W
    )
    owner_val = _owner_value(ord_of_pos, stripe_ids[:, None], L)
    rv_value = jnp.where(
        p1_done,
        jnp.where(any_vote, owner_val, NOOP_VALUE),
        state.rv_value,
    )
    revoked_discovered = state.revoked_discovered + jnp.sum(
        p1_done & any_vote
    )
    rv_phase = jnp.where(p1_done, RV_P2, rv_phase)
    rv_p2a_arrival = jnp.where(
        p1_done[:, :, None] & rv_delivered, t + rv_lat, rv_p2a_arrival
    )
    rv_p1b_arrival = jnp.where(p1_done[:, :, None], INF, rv_p1b_arrival)

    # ---- 4. Global watermark + retire (same formula as Mencius).
    pos_of_ord = jnp.mod(state.head[:, None] + w_iota[None, :], W)
    slot_of_ord = state.head[:, None] + w_iota[None, :]
    chosen_ord = (
        jnp.take_along_axis(status, pos_of_ord, axis=1) == CHOSEN
    ) & (slot_of_ord < state.next_slot[:, None])
    n_contig = jnp.sum(
        jnp.cumprod(chosen_ord.astype(jnp.int32), axis=1), axis=1
    )
    committed_prefix = state.head + n_contig
    executed_global = jnp.min(committed_prefix * L + stripe_ids)
    arrival_ord = jnp.take_along_axis(replica_arrival, pos_of_ord, axis=1)
    global_of_ord = slot_of_ord * L + stripe_ids[:, None]
    retire_ord = (
        chosen_ord & (arrival_ord <= t) & (global_of_ord < executed_global)
    )
    n_retire, retire_mask = ring_retire(retire_ord, state.head)
    head = state.head + n_retire

    status = jnp.where(retire_mask, EMPTY, status)
    slot_value = jnp.where(retire_mask, NO_VALUE, slot_value)
    chosen_value = jnp.where(retire_mask, NO_VALUE, chosen_value)
    propose_tick = jnp.where(retire_mask, INF, state.propose_tick)
    last_send = jnp.where(retire_mask, INF, state.last_send)
    replica_arrival = jnp.where(retire_mask, INF, replica_arrival)
    rv_phase = jnp.where(retire_mask, RV_NONE, rv_phase)
    rv_value = jnp.where(retire_mask, NO_VALUE, rv_value)
    clear3 = retire_mask[:, :, None]
    acc_round = jnp.where(clear3, 0, acc_round)
    voted = jnp.where(clear3, False, voted)
    voted_r1 = jnp.where(clear3, False, voted_r1)
    p2a_arrival = jnp.where(clear3, INF, p2a_arrival)
    p2b_arrival = jnp.where(clear3, INF, p2b_arrival)
    rv_p1a_arrival = jnp.where(clear3, INF, rv_p1a_arrival)
    rv_p1b_arrival = jnp.where(clear3, INF, rv_p1b_arrival)
    rv_p1b_voted = jnp.where(clear3, False, rv_p1b_voted)
    rv_p2a_arrival = jnp.where(clear3, INF, rv_p2a_arrival)
    rv_p2b_arrival = jnp.where(clear3, INF, rv_p2b_arrival)

    # ---- 5. Owner proposals (LIVE owners only; K per tick). Under a
    # workload plan the static knob becomes the per-stripe admission
    # cap (tpu/workload.py).
    space = W - (state.next_slot - head)
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, L)
        adm = workload_mod.admission(wl, wls, wl_writes)
        count = jnp.where(alive, jnp.minimum(adm, space), 0)
    else:
        count = jnp.where(
            alive, jnp.minimum(cfg.slots_per_tick, space), 0
        )
    delta = jnp.mod(w_iota[None, :] - state.next_slot[:, None], W)
    is_new = delta < count[:, None]
    new_ord = state.next_slot[:, None] + delta
    next_slot = state.next_slot + count
    if wl.active:
        wls = workload_mod.finish(wl, wls, t, wl_writes, count, wl_done)
    status = jnp.where(is_new, PROPOSED, status)
    slot_value = jnp.where(
        is_new, _owner_value(new_ord, stripe_ids[:, None], L), slot_value
    )
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    p2a_arrival = jnp.where(
        is_new[:, :, None] & delivered, t + fwd_lat, p2a_arrival
    )

    # ---- 6. Revocation launch: a DEAD stripe lagging the fastest
    # frontier by more than revoke_threshold gets its stalled slots
    # claimed by a live peer (any exists — the revoker identity doesn't
    # change the message pattern at this abstraction): round-1 Phase1as
    # on up to revoke_slots_per_tick in-ring, unchosen, not-yet-revoking
    # slots, EXTENDING next_slot over empty ones so the stripe's ring
    # covers the needed range.
    max_next = jnp.max(jnp.where(alive, next_slot, 0))
    lag = max_next - next_slot
    revoking_stripe = (
        ~alive & (lag > cfg.revoke_threshold) & jnp.any(alive)
    )  # [L]
    # Extend the dead stripe's ring with fresh (EMPTY) slots to revoke.
    ext_space = W - (next_slot - head)
    ext = jnp.where(
        revoking_stripe,
        jnp.minimum(jnp.minimum(lag, cfg.revoke_slots_per_tick), ext_space),
        0,
    )
    ext_new = (delta >= count[:, None]) & (
        delta < (count + ext)[:, None]
    )  # positions allocated for revocation this tick
    # NOTE: delta was computed against pre-extension next_slot shared
    # with step 5; count covers owner proposals (0 for dead stripes).
    next_slot = next_slot + ext
    status = jnp.where(ext_new, PROPOSED, status)  # claimed by revoker
    slot_value = jnp.where(ext_new, NOOP_VALUE, slot_value)
    propose_tick = jnp.where(ext_new, t, propose_tick)
    last_send = jnp.where(ext_new, t, last_send)
    # Target set: in-ring, not chosen, not already under revocation.
    in_ring_now = (
        jnp.mod(w_iota[None, :] - head[:, None], W)
        < (next_slot - head)[:, None]
    )
    target = (
        revoking_stripe[:, None]
        & in_ring_now
        & (status != CHOSEN)
        & (rv_phase == RV_NONE)
    )
    rank = jnp.cumsum(target.astype(jnp.int32), axis=1)
    target = target & (rank <= cfg.revoke_slots_per_tick)
    revocations = state.revocations + jnp.sum(target)
    rv_phase = jnp.where(target, RV_P1, rv_phase)
    rv_p1a_arrival = jnp.where(
        target[:, :, None] & rv_delivered, t + rv_lat, rv_p1a_arrival
    )

    # ---- 7. Owner retries (live owners, round-0 slots not revoked).
    timed_out = (
        (status == PROPOSED)
        & alive[:, None]
        & (rv_phase == RV_NONE)
        & (t - last_send >= cfg.retry_timeout)
    )
    p2a_arrival = jnp.where(
        timed_out[:, :, None], t + rv_lat, p2a_arrival
    )
    last_send = jnp.where(timed_out, t, last_send)

    new_executed_global = jnp.maximum(state.executed_global, executed_global)
    # Telemetry: revocation Phase1as are the phase-1 plane; owner
    # proposals + retries the phase-2 plane; leader_changes counts the
    # slots a revoker claimed from a dead stripe.
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase1_msgs=jnp.sum(target[:, :, None] & rv_delivered),
        phase2_msgs=jnp.sum(is_new[:, :, None] & delivered)
        + A * jnp.sum(timed_out),
        commits=committed - state.committed,
        executes=new_executed_global - state.executed_global,
        drops=jnp.sum(is_new[:, :, None] & ~delivered)
        + jnp.sum(target[:, :, None] & ~rv_delivered),
        retries=jnp.sum(timed_out),
        leader_changes=revocations - state.revocations,
        queue_depth=jnp.sum(next_slot - head),
        queue_capacity=L * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    return BatchedVanillaMenciusState(
        next_slot=next_slot,
        head=head,
        status=status,
        slot_value=slot_value,
        propose_tick=propose_tick,
        last_send=last_send,
        replica_arrival=replica_arrival,
        chosen_value=chosen_value,
        committed_prefix=committed_prefix,
        acc_round=acc_round,
        voted=voted,
        voted_r1=voted_r1,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        alive=alive,
        rv_phase=rv_phase,
        rv_value=rv_value,
        rv_p1a_arrival=rv_p1a_arrival,
        rv_p1b_arrival=rv_p1b_arrival,
        rv_p1b_voted=rv_p1b_voted,
        rv_p2a_arrival=rv_p2a_arrival,
        rv_p2b_arrival=rv_p2b_arrival,
        executed_global=new_executed_global,
        committed=committed,
        committed_real=committed_real,
        revocations=revocations,
        revoked_discovered=revoked_discovered,
        deaths=deaths,
        choose_violations=choose_violations,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedVanillaMenciusConfig,
    state: BatchedVanillaMenciusState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedVanillaMenciusState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedVanillaMenciusConfig,
    state: BatchedVanillaMenciusState,
    t,
) -> dict:
    L = cfg.num_servers
    stripe_ids = jnp.arange(L, dtype=jnp.int32)
    # THE revocation safety property: no slot ever chosen twice with
    # different values (the device-side ledger).
    choose_once = state.choose_violations == 0
    # Promise discipline: an acceptor that voted round 1 promised round 1.
    promise_ok = jnp.all(~state.voted_r1 | (state.acc_round == 1))
    watermark_ok = state.executed_global <= jnp.min(
        state.committed_prefix * L + stripe_ids
    )
    window_ok = jnp.all(
        (state.head <= state.next_slot)
        & (state.next_slot - state.head <= cfg.window)
    )
    head_ok = jnp.all(state.head <= state.committed_prefix)
    books_ok = (
        state.committed_real <= state.committed
    ) & (state.revoked_discovered <= state.revocations)
    return {
        "choose_once": choose_once,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "promise_ok": promise_ok,
        "watermark_ok": watermark_ok,
        "window_ok": window_ok,
        "head_ok": head_ok,
        "books_ok": books_ok,
    }


def stats(
    cfg: BatchedVanillaMenciusConfig,
    state: BatchedVanillaMenciusState,
    t,
) -> dict:
    real = int(state.committed_real)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (real + 1) // 2)).argmax())
        if real
        else -1
    )
    return {
        "ticks": int(t),
        "committed": int(state.committed),
        "committed_real": real,
        "executed_global": int(state.executed_global),
        "revocations": int(state.revocations),
        "revoked_discovered": int(state.revoked_discovered),
        "deaths": int(state.deaths),
        "choose_violations": int(state.choose_violations),
        "latency_p50_ticks": p50,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedVanillaMenciusConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedVanillaMenciusConfig(
        num_servers=4, window=16, slots_per_tick=2,
        retry_timeout=8, faults=faults, workload=workload,
    )
