"""Production-lifecycle subsystem: in-graph log compaction, exactly-once
client session tables, and traced acceptor reconfiguration — the layer
that lets a serve-mode run of a batched backend run for UNBOUNDED
durations (the ROADMAP "production lifecycle" item; the reference's
protocol-agnostic ``compact/`` and ``clienttable/`` libraries and the
matchmakermultipaxos online-reconfiguration protocol, rebuilt TPU-first
as one plan object).

Every backend's slot ring already recycles ring POSITIONS (position =
slot mod W), so device memory is constant by construction — what is
bounded is the NUMBERING horizon: absolute per-group slot numbers
(``head``/``next_slot``), the global read-path numbering ``slot*G + g``,
and the command-id space all live in int32 and a long-lived serve loop
marches them toward the ``slot_horizon_ok`` wall, where the backend
fails loudly rather than silently mis-ordering. The three legs of
:class:`LifecyclePlan` close that and the two other open lifecycle
gaps, all INSIDE the compiled tick:

  * **Watermark-driven window rotation** (``rotate_every > 0``) — when
    every replica's executed watermark (the minimum group head) clears
    the threshold, every absolute slot number and slot-derived command
    id REBASES down by a multiple of the backend's alignment quantum
    (a masked subtract over the slot planes, in place: the batched
    analog of ``compact/`` garbage-collecting the retired log prefix).
    Ring positions are slot mod W and every role assignment is slot mod
    {W, NC, P, U}, so a shift that is a multiple of the backend's
    alignment (:meth:`LifecyclePlan.validate` ``align=``) is an EXACT
    renumbering: the rotated run replays the unrotated run bit for bit
    modulo the shift (pinned by ``tests/test_lifecycle.py``
    rotation-exactness), the log is logically infinite in constant
    int32 horizon, and offset clocks — already head-relative — never
    move. A rotation counter feeds the telemetry ring's ``rotations``
    column, and the span sampler's slot ids stay stable across rolls
    because backends stamp spans with ``rot_base``-absolute numbering.
    (Two caveats. First, inherited from the read path's AMS_FLOOR
    saturation: an acceptor whose last vote is >2^14 retired slots
    stale reconstructs its MaxSlot differently across a roll — the
    same approximation class the saturation floor already accepts.
    Second, the PROTOCOL state is horizon-free but the cumulative
    BOOKKEEPING is not: ``rot_base`` (total rebased slots), the
    rot_base-absolute span ids, and the session-table completion ids
    are int32 accumulators like ``committed`` and the telemetry
    totals, so they wrap mod 2^32 after ~2^31 retired slots — the
    exported numbering aliases there while the rebased protocol state
    stays exact, the same accepted-wrap contract the dtype policy
    documents for every other cumulative counter.)

  * **Client session table** (``sessions > 0``) — a ``[L, S]`` per-lane
    table of ``(last_command_id, cached_result)`` (the batched
    ``clienttable/``), recording every client-visible completion:
    per-lane completion ``i`` is command id ``i`` owned by session
    ``i mod S``, and the table keeps each session's LARGEST completed
    id plus its cached result (the completion tick). Duplicate
    submissions — a client re-sending an op whose reply was lost,
    drawn per lane at ``resubmit_rate`` from the lifecycle PRNG stream
    — are answered FROM THE CACHE without re-proposing: they never
    enter the admission path, so the protocol history is bit-identical
    to the resubmit-free twin (exactly-once by construction, not by
    filtering), and the workload engine's conservation invariant
    (``workload_ok``) still holds exactly — when both subsystems are
    active the table's completion totals reconcile against
    ``WorkloadState.completed`` one for one. This composes with (not
    replaces) the two lower dedup layers: ``FaultPlan.dup_rate``'s
    eager message duplicates (receivers dedup by arrival-clock
    min-write) and the flagship ``state_machine="kv"`` client table
    (re-ISSUED ids filtered at execution).

  * **Traced acceptor reconfiguration** (``reconfig=True``) — the
    acceptor membership mask and its epoch live in STATE, like the
    workload engine's traced rate: the serve control plane swaps a
    crashed acceptor, or grows/shrinks the live set, between chunks
    with ZERO recompiles (:func:`set_membership` bumps the traced
    epoch; the jit cache stays flat — pinned by the
    ``trace-lifecycle-retrace`` analysis rule). Inside the tick an
    epoch switch is the matchmaker i/i+1 handoff collapsed to one
    tick: the flagship bumps the round and re-promises via the
    existing ``multipaxos_p1_promise`` kernel plane (an oracle
    all-acceptor read, a superset of any f+1 read quorum), in-flight
    votes clear and re-propose to the new membership, and OLD-EPOCH GC
    clears pending traffic to departed acceptors immediately while the
    epoch's in-flight slots drain behind a GC watermark (the
    Reconfigurer pipeline). Departed acceptors never receive another
    message (the mask gates the Phase2a/retry sends); chosen slots
    keep their old-epoch vote records until they retire, so quorum
    certificates stay intact.

``LifecyclePlan.none()`` (the default on every lifecycle-threaded
config) is a STRUCTURAL no-op: every :class:`LifecycleState` leaf is
zero-sized, no tick equation consumes them, no PRNG key is ever
derived — XLA emits the exact pre-lifecycle program and default runs
stay bit-identical to the pre-PR goldens (pinned by
``tests/test_lifecycle.py``; the ``lifecycle-noop`` analysis rule pins
the structure, mirroring ``trace-workload-noop``).

Determinism contract: all lifecycle randomness derives from the tick's
own threefry key via ``fold_in`` with :data:`LIFECYCLE_SALT`, disjoint
from the fault (0x5EED) and workload (0x10AD) streams — which is what
makes the exactly-once test EXACT: a resubmitting run's protocol
history equals the resubmit-free twin's bit for bit.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import packing
from frankenpaxos_tpu.tpu.common import bit_delivered

# Stream id folded into a tick's key before drawing any lifecycle
# randomness (the session-table resubmission draw). Distinct from
# faults.FAULT_SALT and workload.WORKLOAD_SALT.
LIFECYCLE_SALT = 0x11FE


@dataclasses.dataclass(frozen=True)
class LifecyclePlan:
    """One production-lifecycle shape. Frozen + hashable: lives inside
    the static backend config (a ``jax.jit`` static argument), exactly
    like :class:`~frankenpaxos_tpu.tpu.faults.FaultPlan` and
    :class:`~frankenpaxos_tpu.tpu.workload.WorkloadPlan`. The plan
    fixes STRUCTURE (rotation quantum, table geometry, whether the
    membership axis exists); the sweepable/steerable quantities —
    membership, epoch, the force-rotation latch — are traced state
    (:class:`LifecycleState`), so the serve control plane steers them
    with zero recompiles."""

    # Window rotation: rebase the slot numbering once every group's
    # executed watermark (head) clears this many slots. 0 = off. Must
    # be a positive multiple of the backend's alignment quantum (the
    # lcm of every "slot mod k" role assignment — ``validate(align=)``).
    rotate_every: int = 0
    # Client session table: sessions per lane (0 = off) and the
    # per-lane per-tick probability that a client re-submits its most
    # recent completed command (reply-loss model; the duplicate is
    # answered from the cache, never re-proposed).
    sessions: int = 0
    resubmit_rate: float = 0.0
    # Session expiry: a cached record idle for more than this many
    # ticks (t - completion tick > ttl, a TRACED comparison) demotes
    # to the unset sentinel — the real expiry knob the PR 11 follow-up
    # asked for (records used to demote only at rotation margin). A
    # resubmission that finds its record expired counts as a resubmit
    # WITHOUT a cache hit (the reply-loss client would re-propose in a
    # real deployment; here the miss is counted honestly). 0 = never.
    session_ttl: int = 0
    # Traced acceptor reconfiguration: carry a traced membership mask +
    # epoch over the backend's acceptor axis. False = the axis does not
    # exist (no mask gating, no epoch compare — the pre-plan program).
    reconfig: bool = False

    # -- structural predicates (all trace-time Python bools) ------------

    @property
    def compaction(self) -> bool:
        return self.rotate_every > 0

    @property
    def has_sessions(self) -> bool:
        return self.sessions > 0

    @property
    def active(self) -> bool:
        return self.compaction or self.has_sessions or self.reconfig

    @classmethod
    def none(cls) -> "LifecyclePlan":
        """The structural no-op plan: every helper compiles to the
        identity, every state leaf is zero-sized, and XLA emits the
        exact pre-lifecycle program."""
        return cls()

    def validate(self, align: int = 1) -> None:
        """Config-time validation; every lifecycle-threaded backend's
        ``__post_init__`` calls this with its alignment quantum
        ``align`` (the lcm of every modulus its tick applies to
        absolute slot numbers/ids — ring width, client round-robin,
        proxy/unbatcher assignment). A rotation shift that is a
        multiple of ``align`` is an exact renumbering; anything else
        would silently remap roles mid-run."""
        assert self.rotate_every >= 0
        if self.compaction:
            assert align >= 1
            assert self.rotate_every % align == 0, (
                f"lifecycle.rotate_every={self.rotate_every} must be a "
                f"multiple of this backend's rotation alignment "
                f"({align}: the lcm of its slot-mod role assignments)"
            )
        assert self.sessions >= 0
        assert 0.0 <= self.resubmit_rate < 1.0
        if self.resubmit_rate > 0.0:
            assert self.has_sessions, (
                "lifecycle.resubmit_rate needs sessions > 0 (the cache "
                "that answers the duplicate)"
            )
        assert self.session_ttl >= 0
        if self.session_ttl > 0:
            assert self.has_sessions, (
                "lifecycle.session_ttl needs sessions > 0 (the table "
                "whose records expire)"
            )

    # -- serialization (one schema with the fault/workload plans) --------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "LifecyclePlan":
        return cls(**d)


def alignment(*moduli: int) -> int:
    """The rotation alignment quantum: the lcm of every ``slot mod k``
    role assignment a backend's tick applies to absolute slot numbers.
    Backends compute this once in ``__post_init__`` and pass it to
    :meth:`LifecyclePlan.validate`."""
    out = 1
    for m in moduli:
        if m and m > 1:
            out = math.lcm(out, m)
    return out


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LifecycleState:
    """Device-resident lifecycle state, carried in a lifecycle-threaded
    backend's ``*State`` (lane axis L = the backend's proposer axis,
    matching the workload engine's). Every leaf is ZERO-SIZED for the
    legs a plan leaves off — a ``LifecyclePlan.none()`` state is
    all-empty, adds zero ops, and keeps the scan carry bit-identical to
    the pre-lifecycle program. Counters are int32 (the dtype policy's
    accumulator width); masks are bool."""

    # Window rotation (compaction).
    rot_count: jnp.ndarray  # [] rotations fired (cumulative) | [0]
    rot_base: jnp.ndarray  # [] cumulative rebased slots (absolute base) | [0]
    rot_force: jnp.ndarray  # [] host-latched force-rotation request | [0]
    # Client session table (sessions > 0). S = plan.sessions.
    sess_total: jnp.ndarray  # [L] client-visible completions per lane | [0]
    sess_last: jnp.ndarray  # [L, S] largest completed id per session (-1)
    sess_res: jnp.ndarray  # [L, S] cached result (completion tick; -1)
    # Bit-packed occupancy (make_state(packed=True)): liveness moves to
    # a [L, S/32] int32 bitmap (tpu/packing.py) and the -1 sentinel
    # sweeps over the two int32 planes above stop — dead cells keep
    # stale values, masked back to -1 by canonical_sessions(). [L, 0]
    # when sessions are on but unpacked; [0, 0] when sessions are off.
    sess_occ: jnp.ndarray
    resubmits: jnp.ndarray  # [] duplicate submissions drawn | [0]
    cache_hits: jnp.ndarray  # [] duplicates answered from the cache | [0]
    expired: jnp.ndarray  # [] records demoted by session_ttl | [0]
    # Traced acceptor reconfiguration (reconfig=True).
    epoch: jnp.ndarray  # [] target epoch (host-bumped, traced) | [0]
    applied: jnp.ndarray  # [] epoch the tick has applied | [0]
    acc_mask: jnp.ndarray  # [acceptor axis...] live membership | [0]
    next_mask: jnp.ndarray  # [acceptor axis...] target membership | [0]
    gc_watermark: jnp.ndarray  # [L] old epoch retired once head >= | [0]
    old_live: jnp.ndarray  # [L] old epoch not yet GCd | [0]
    epochs_gcd: jnp.ndarray  # [] per-lane old-epoch GCs (cumulative) | [0]


def make_state(
    plan: LifecyclePlan,
    lanes: int,
    acceptor_shape: Tuple[int, ...] = (),
    packed: bool = False,
) -> LifecycleState:
    """The backend's lifecycle state. ``acceptor_shape`` is the shape
    of the backend's acceptor membership axis (e.g. ``(A, G)`` for the
    flagship, ``(R, C, G)`` for the compartmentalized grid); only read
    when ``plan.reconfig``. Leaves for disabled legs are zero-sized so
    the none plan carries nothing. ``packed`` (the backend's
    ``pack_planes`` knob) carries session liveness as the
    ``sess_occ`` bitmap instead of -1 sentinel sweeps."""
    z32 = jnp.int32
    scalar_rot = () if plan.compaction else (0,)
    Ls = lanes if plan.has_sessions else 0
    S = plan.sessions if plan.has_sessions else 0
    scalar_sess = () if plan.has_sessions else (0,)
    scalar_rc = () if plan.reconfig else (0,)
    mask_shape = acceptor_shape if plan.reconfig else (0,)
    Lr = lanes if plan.reconfig else 0
    if plan.reconfig:
        assert acceptor_shape, (
            "LifecyclePlan(reconfig=True) needs the backend's acceptor "
            "axis shape (init_state must pass acceptor_shape=)"
        )
    return LifecycleState(
        rot_count=jnp.zeros(scalar_rot, z32),
        rot_base=jnp.zeros(scalar_rot, z32),
        rot_force=jnp.zeros(scalar_rot, z32),
        sess_total=jnp.zeros((Ls,), z32),
        sess_last=jnp.full((Ls, S), -1, z32),
        sess_res=jnp.full((Ls, S), -1, z32),
        sess_occ=(
            packing.make_occ(Ls, S)
            if (packed and plan.has_sessions)
            else jnp.zeros((Ls, 0), z32)
        ),
        resubmits=jnp.zeros(scalar_sess, z32),
        cache_hits=jnp.zeros(scalar_sess, z32),
        expired=jnp.zeros(() if plan.session_ttl > 0 else (0,), z32),
        epoch=jnp.zeros(scalar_rc, z32),
        applied=jnp.zeros(scalar_rc, z32),
        acc_mask=jnp.ones(mask_shape, bool),
        next_mask=jnp.ones(mask_shape, bool),
        gc_watermark=jnp.full((Lr,), -1, z32),
        old_live=jnp.zeros((Lr,), bool),
        epochs_gcd=jnp.zeros(scalar_rc, z32),
    )


def lifecycle_key(key: jnp.ndarray) -> jnp.ndarray:
    """The per-tick lifecycle stream. Callers must only derive this
    when the session leg draws (resubmit_rate > 0) so every other path
    touches no keys at all — the disjoint-stream contract that keeps
    the exactly-once twin comparison bit-exact."""
    return jax.random.fold_in(key, LIFECYCLE_SALT)


# ---------------------------------------------------------------------------
# Window rotation (compaction). Call order inside a backend's tick:
#     shift, lcs = rotation_shift(plan, lcs, min_head)     # after planes
#     ... telemetry record(rotations=(shift > 0)) ...
#     head = head - shift; ids = shift_ids(ids, shift * G) # rebase
# ---------------------------------------------------------------------------


def rotation_shift(
    plan: LifecyclePlan,
    lcs: LifecycleState,
    min_head,
    align: int,
    margin: int = 0,
) -> Tuple[jnp.ndarray, LifecycleState]:
    """This tick's rotation shift: a traced scalar multiple of the
    backend's alignment quantum ``align`` (0 = no rotation), plus the
    updated counters. Fires when the global executed watermark
    (``min_head``, the minimum group head AFTER this tick's
    retirement) clears ``rotate_every`` — or EARLY, when the host
    latched :func:`request_rotation` (the latch persists until at
    least one alignment quantum has retired). The roll rebases by the
    largest whole multiple of ``align`` that keeps ``margin`` retired
    slots behind the watermark UNROLLED: ``margin`` is the backend's
    id-staleness bound (for the flagship, W — the furthest back any
    LIVE id record, e.g. a client's last issued command, can point),
    so the rebase never drives a live id negative and stays an exact
    renumbering. Post-roll heads are bounded by margin + align + W."""
    assert plan.compaction
    # Whole alignment quanta retired beyond the staleness margin.
    quanta = jnp.maximum(min_head - margin, 0) // align
    fire = (min_head >= plan.rotate_every) | (lcs.rot_force > 0)
    shift = jnp.where(fire & (quanta > 0), quanta * align, 0)
    fired = (shift > 0).astype(jnp.int32)
    lcs = dataclasses.replace(
        lcs,
        rot_count=lcs.rot_count + fired,
        rot_base=lcs.rot_base + shift,
        rot_force=jnp.where(fired > 0, 0, lcs.rot_force),
    )
    return shift, lcs


def shift_counts(x: jnp.ndarray, shift) -> jnp.ndarray:
    """Rebase an always-nonnegative absolute-slot field (heads,
    frontiers, per-replica watermarks) by the rotation shift."""
    return (x - shift).astype(x.dtype)


def shift_ids(x: jnp.ndarray, shift, floor=None) -> jnp.ndarray:
    """Rebase a slot-derived id/number field that uses negative
    sentinels (-1 unset, -2 noop): only nonnegative entries move, so
    sentinels survive the roll. ``floor`` clamps the rebased value —
    for STALE watermark-style bounds (e.g. a read bound deferred
    across the roll by a partition): any bound below the rotation
    threshold is already satisfied by every live watermark, so
    clamping it to the floor leaves the serve condition's outcome
    unchanged while keeping the field's nonnegativity invariant."""
    shifted = x - shift
    if floor is not None:
        shifted = jnp.maximum(shifted, floor)
    return jnp.where(x >= 0, shifted, x).astype(x.dtype)


# ---------------------------------------------------------------------------
# Client session table
# ---------------------------------------------------------------------------


def sessions_step(
    plan: LifecyclePlan,
    lcs: LifecycleState,
    key: jnp.ndarray,
    t,
    completions: jnp.ndarray,
) -> LifecycleState:
    """One tick of the session table. ``completions`` is the per-lane
    count of CLIENT-VISIBLE completions this tick (the same quantity
    the workload engine's ``finish`` receives — which is what makes the
    cross-subsystem conservation check exact).

    Two halves, both exact array math (no per-entry loops):

      * resubmissions: per lane, with ``resubmit_rate``, the client
        whose command completed MOST RECENTLY re-submits it (the
        reply-was-lost model). Its id is ``sess_total - 1``, which by
        construction is the table entry of session ``(sess_total-1) %
        S`` — a guaranteed cache hit once the lane has completed
        anything. The duplicate is answered from the cache: counted,
        never admitted, so the protocol planes never see it.
      * recording: per-lane completion ``i`` (0-based, cumulative) is
        command id ``i`` owned by session ``i % S``; each session
        entry keeps the LARGEST id that landed on it this tick (the
        per-session last-writer over the batch, computed closed-form
        from the cumulative interval) and caches its result — the
        completion tick ``t``."""
    assert plan.has_sessions
    L, S = lcs.sess_last.shape
    # Packed occupancy (make_state(packed=True)) is a STRUCTURAL
    # trace-time predicate, read off the bitmap's shape like every
    # other plan gate.
    packed = lcs.sess_occ.shape[-1] > 0
    completions = completions.astype(jnp.int32)
    resubmits = lcs.resubmits
    cache_hits = lcs.cache_hits
    if plan.resubmit_rate > 0.0:
        bits = jax.random.bits(lifecycle_key(key), (L,))
        resub = ~bit_delivered(bits, 0, plan.resubmit_rate)  # [L]
        has_done = lcs.sess_total > 0
        last_sess = jnp.where(
            has_done, (lcs.sess_total - 1) % S, 0
        )  # [L]
        cached = (
            jnp.take_along_axis(lcs.sess_last, last_sess[:, None], axis=1)[
                :, 0
            ]
            == lcs.sess_total - 1
        )
        if packed:
            # Dead cells keep stale ids under the bitmap scheme, so
            # the cache test must ALSO see the bit live — exactly the
            # sentinel test the unpacked twin's -1 write performs.
            cached = cached & packing.occ_get(lcs.sess_occ, last_sess)
        hit = resub & has_done & cached
        resubmits = resubmits + jnp.sum(resub)
        cache_hits = cache_hits + jnp.sum(hit)
    # Record this tick's completions: session j's candidate id is the
    # largest c < after with c % S == j; it lands iff c >= before.
    before = lcs.sess_total  # [L]
    after = before + completions
    j = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    cand = after[:, None] - 1 - jnp.mod(after[:, None] - 1 - j, S)
    wrote = (cand >= before[:, None]) & (cand >= 0)
    sess_last = jnp.where(wrote, cand, lcs.sess_last)
    sess_res = jnp.where(wrote, jnp.asarray(t, jnp.int32), lcs.sess_res)
    sess_occ = lcs.sess_occ
    if packed:
        sess_occ = packing.occ_set(sess_occ, wrote)
    expired = lcs.expired
    if plan.session_ttl > 0:
        # Expiry (the traced-threshold knob): records idle past the
        # ttl demote to the unset sentinel, AFTER this tick's
        # recording so a just-completed record is never expired by the
        # same tick that wrote it. sess_total is untouched — it is the
        # cumulative completion count the workload reconciliation
        # reads, so conservation (sum(sess_total) == completed) holds
        # across expiries exactly.
        if packed:
            # The bitmap scheme's HBM win: expiry clears 1-bit flags
            # and never rewrites the two [L, S] int32 planes (their
            # stale values are masked by canonical_sessions on every
            # read path). sess_res is only consulted under a live bit,
            # where it is always current — same idle set as unpacked.
            live = packing.occ_unpack(sess_occ, S)
            idle = live & (
                jnp.asarray(t, jnp.int32) - sess_res > plan.session_ttl
            )
            sess_occ = packing.occ_clear(sess_occ, idle)
        else:
            idle = (sess_res >= 0) & (
                jnp.asarray(t, jnp.int32) - sess_res > plan.session_ttl
            )
            sess_last = jnp.where(idle, -1, sess_last)
            sess_res = jnp.where(idle, -1, sess_res)
        expired = expired + jnp.sum(idle)
    return dataclasses.replace(
        lcs,
        sess_total=after,
        sess_last=sess_last,
        sess_res=sess_res,
        sess_occ=sess_occ,
        resubmits=resubmits,
        cache_hits=cache_hits,
        expired=expired,
    )


def canonical_sessions(
    plan: LifecyclePlan, lcs: LifecycleState
) -> LifecycleState:
    """The UNPACKED-EQUIVALENT view of a session table: under the
    packed occupancy bitmap, dead cells keep stale ``sess_last`` /
    ``sess_res`` values (expiry clears only their bit); this masks
    them back to the -1 sentinels, so ``canonical_sessions(packed
    run) == unpacked run`` EXACTLY — the bit-identity contract
    ``tests/test_packing.py`` pins 3-seed. Identity on unpacked (and
    session-less) states."""
    if not plan.has_sessions or lcs.sess_occ.shape[-1] == 0:
        return lcs
    S = lcs.sess_last.shape[1]
    live = packing.occ_unpack(lcs.sess_occ, S)
    return dataclasses.replace(
        lcs,
        sess_last=jnp.where(live, lcs.sess_last, -1),
        sess_res=jnp.where(live, lcs.sess_res, -1),
    )


def live_sessions(plan: LifecyclePlan, lcs: LifecycleState) -> jnp.ndarray:
    """Traced scalar: DISTINCT sessions currently live in the table
    (the denominator of the million-session bench leg). Popcount of
    the occupancy bitmap when packed, the sentinel census otherwise."""
    if not plan.has_sessions:
        return jnp.zeros((), jnp.int32)
    if lcs.sess_occ.shape[-1] > 0:
        S = lcs.sess_last.shape[1]
        return jnp.sum(
            packing.occ_unpack(lcs.sess_occ, S).astype(jnp.int32)
        )
    return jnp.sum((lcs.sess_last >= 0).astype(jnp.int32))


# ---------------------------------------------------------------------------
# Traced acceptor reconfiguration
# ---------------------------------------------------------------------------


def reconfig_switch(
    plan: LifecyclePlan, lcs: LifecycleState
) -> jnp.ndarray:
    """Traced scalar bool: a host-requested epoch change is pending
    this tick. Backends run their i/i+1 handoff (round bump + phase-1
    re-promise + vote clear + old-epoch GC) under it."""
    assert plan.reconfig
    return lcs.epoch != lcs.applied


def reconfig_applied(
    plan: LifecyclePlan,
    lcs: LifecycleState,
    switch,
    next_slot: jnp.ndarray,
    head: jnp.ndarray,
) -> LifecycleState:
    """Commit an epoch switch: install the target membership, arm the
    old epoch's GC watermark at the allocation frontier (every slot the
    old membership may have voted on retires before the epoch is
    collected — the Reconfigurer GC pipeline), and advance the applied
    epoch. Also runs the per-tick GC check itself (head passing the
    watermark retires the old epoch), so backends call this once per
    tick unconditionally when ``plan.reconfig``."""
    assert plan.reconfig
    acc_mask = jnp.where(switch, lcs.next_mask, lcs.acc_mask)
    gc_watermark = jnp.where(switch, next_slot, lcs.gc_watermark)
    old_live = lcs.old_live | jnp.broadcast_to(switch, lcs.old_live.shape)
    applied = jnp.where(switch, lcs.epoch, lcs.applied)
    gc_now = old_live & (head >= gc_watermark)
    return dataclasses.replace(
        lcs,
        acc_mask=acc_mask,
        gc_watermark=gc_watermark,
        old_live=old_live & ~gc_now,
        applied=applied,
        epochs_gcd=lcs.epochs_gcd + jnp.sum(gc_now),
    )


# ---------------------------------------------------------------------------
# Host-side control verbs (the serve control plane; zero recompiles).
# ---------------------------------------------------------------------------


def set_membership(lcs: LifecycleState, mask) -> LifecycleState:
    """The reconfiguration verb: install a new target membership and
    bump the traced epoch — the next compiled tick runs the i/i+1
    handoff. ``mask`` broadcasts over the acceptor axis (so a scalar
    ``True`` restores full membership); membership and epoch are
    traced state, so the SAME compiled program keeps running (pinned
    by the ``trace-lifecycle-retrace`` rule)."""
    assert lcs.acc_mask.ndim >= 1 and lcs.acc_mask.size > 0, (
        "set_membership needs a LifecyclePlan(reconfig=True) config"
    )
    new = jnp.broadcast_to(
        jnp.asarray(mask, bool), lcs.acc_mask.shape
    )
    return dataclasses.replace(
        lcs, next_mask=new, epoch=lcs.epoch + 1
    )


def swap_acceptor(lcs: LifecycleState, index: int) -> LifecycleState:
    """Convenience verb: swap the acceptor at ``index`` of a flat
    ``[A, G]`` acceptor axis out (the crashed node leaves the
    configuration; re-enable later with ``set_membership(lcs, True)``
    or a full mask). Only meaningful on a 2-D axis: on a grid-shaped
    axis (``[R, C, G]``) masking a whole leading ROW would cut every
    column-transversal write quorum — address a single cell with an
    explicit :func:`set_membership` mask instead."""
    assert lcs.acc_mask.ndim == 2, (
        "swap_acceptor addresses a flat [A, G] acceptor axis; this "
        f"backend's axis is {lcs.acc_mask.shape} — masking a whole "
        "leading row would kill every write quorum. Pass an explicit "
        "single-cell mask to set_membership instead."
    )
    mask = jnp.ones(lcs.acc_mask.shape, bool).at[index].set(False)
    return set_membership(lcs, mask)


def request_rotation(lcs: LifecycleState) -> LifecycleState:
    """The rotation verb: latch a force-rotation request — the next
    compiled tick rolls the window down to the largest whole quantum
    the executed watermark has cleared (a no-op until at least one
    quantum retired; the latch persists until a roll fires)."""
    assert lcs.rot_force.ndim == 0, (
        "request_rotation needs a LifecyclePlan(rotate_every > 0) config"
    )
    return dataclasses.replace(
        lcs, rot_force=jnp.ones((), jnp.int32)
    )


# ---------------------------------------------------------------------------
# Invariants + host reporting
# ---------------------------------------------------------------------------


def invariants_ok(
    plan: LifecyclePlan,
    lcs: LifecycleState,
    workload_completed: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Traced scalar bool: the lifecycle bookkeeping is conserved.
    Session ids never run ahead of the lane's completion count, every
    cached result is stamped exactly when its id is, duplicates
    answered never exceed duplicates drawn — and, when the caller also
    runs the workload engine, the table's completion totals reconcile
    against ``WorkloadState.completed`` exactly (the extended
    conservation contract: exactly-once accounting and window
    conservation are the same books). True (a constant) when the plan
    is inactive."""
    ok = jnp.asarray(True)
    if plan.has_sessions:
        # Under the packed bitmap the conservation laws hold of the
        # canonical (sentinel-masked) view — dead cells' stale values
        # are storage noise, not bookkeeping.
        lcs = canonical_sessions(plan, lcs)
        S = lcs.sess_last.shape[1]
        ok = (
            ok
            & jnp.all(lcs.sess_last < lcs.sess_total[:, None])
            & jnp.all(lcs.sess_last >= -1)
            & jnp.all((lcs.sess_last >= 0) == (lcs.sess_res >= 0))
            & (lcs.cache_hits <= lcs.resubmits)
            # Live records never exceed what the lane has completed (or
            # the table width) — expiry only ever SHRINKS the live set,
            # so this holds with and without a ttl.
            & jnp.all(
                jnp.sum((lcs.sess_last >= 0).astype(jnp.int32), axis=1)
                <= jnp.minimum(lcs.sess_total, S)
            )
        )
        if plan.session_ttl > 0:
            ok = ok & (lcs.expired >= 0)
        if workload_completed is not None:
            # Conservation reconciles ACROSS expiries: sess_total is
            # cumulative and expiry never touches it.
            ok = ok & (jnp.sum(lcs.sess_total) == workload_completed)
    if plan.compaction:
        # rot_base is a CUMULATIVE counter (total rebased slots — see
        # the wrap note in the module docstring), so like every int32
        # accumulator under the dtype policy it wraps at extreme
        # horizons; only the wrap-safe half is asserted.
        ok = ok & (lcs.rot_count >= 0)
    if plan.reconfig:
        # epochs_gcd counts PER-LANE collections (lanes drain their
        # old epoch independently behind their own heads), so it is
        # bounded by applied switches x lanes.
        ok = (
            ok
            & (lcs.applied <= lcs.epoch)
            & jnp.all(~lcs.old_live | (lcs.gc_watermark >= 0))
            & (lcs.epochs_gcd <= lcs.applied * lcs.old_live.shape[0])
        )
    return ok


def summary(plan: LifecyclePlan, lcs: LifecycleState) -> dict:
    """Host roll-up of the lifecycle state (one coalesced pull):
    rotation count/base, session-table totals and cache hits, and the
    reconfiguration epoch/GC counters."""
    out = {"active": plan.active}
    if not plan.active:
        return out
    lcs = jax.device_get(lcs)
    if plan.compaction:
        out.update(
            rotations=int(lcs.rot_count),
            rotated_slots=int(lcs.rot_base),
            rotate_every=plan.rotate_every,
        )
    if plan.has_sessions:
        import numpy as np

        out.update(
            sessions=plan.sessions,
            completions_recorded=int(np.sum(lcs.sess_total)),
            distinct_live=int(live_sessions(plan, lcs)),
            packed_occupancy=bool(lcs.sess_occ.shape[-1] > 0),
            resubmits=int(lcs.resubmits),
            cache_hits=int(lcs.cache_hits),
        )
        if plan.session_ttl > 0:
            out.update(
                session_ttl=plan.session_ttl,
                expired=int(lcs.expired),
            )
    if plan.reconfig:
        import numpy as np

        out.update(
            epoch=int(lcs.epoch),
            epoch_applied=int(lcs.applied),
            live_acceptors=int(np.sum(lcs.acc_mask)),
            acceptor_axis=int(lcs.acc_mask.size),
            epochs_gcd=int(lcs.epochs_gcd),
        )
    return out
