"""Batched EPaxos / Simple BPaxos as a single XLA program (BASELINE
config 3: dependency-graph protocols at scale).

The reference's hot loop for the EPaxos family is commit-then-execute
through a dependency graph: committed instances execute as eligible
strongly-connected components in reverse topological order
(``depgraph/TarjanDependencyGraph.scala:149``, ``epaxos/Replica.scala``).
Re-designed TPU-first:

  * ``C`` columns (one per replica/instance leader, the (replica, i)
    instance space of ``epaxos/Replica.scala``), each owning a ring of
    ``W`` in-flight instances — struct-of-arrays state, shardable over a
    device mesh along ``C``.
  * Dependency sets are PREFIX-SHAPED per column — the
    ``InstancePrefixSet`` / top-k compression of the reference
    (``epaxos/InstancePrefixSet.scala``). Rather than storing a [C, W, C]
    watermark matrix (quadratic in C — the round-3 backend's scaling
    blocker), an instance's dependency vector is FACTORED: it equals the
    global proposal frontier at its propose tick (``fpre[t]``), bumped to
    the post-tick frontier (``fpost[t]``) for the peer columns whose
    same-tick proposals it saw. Per instance that leaves one tick index
    and a C-bit visibility mask packed into ``ceil(C/32)`` uint32 words:
    O(C*W*C/32) memory instead of O(C*W*C*4) bytes.
  * Every instance depends on all its own-column predecessors (a replica
    serializes its own instances), so execution within a column is in
    order and the executed set is always a contiguous per-column prefix —
    the ``executed`` bitmap of the round-3 backend is replaced by the
    ``head`` watermark itself (slots retire the tick they execute).
  * The dependency-graph execute pass is a GREATEST-FIXPOINT over the
    per-column watermark vector ``m``: the largest ``m >= head`` such
    that every instance below ``m`` is committed and its dependency
    vector lies below ``m``. Because dependency vectors are factored
    through the frontier history, each fixpoint iteration costs
    O(H*C) to score the ticks plus O(C*W*C/32) of bitmask ANDs —
    no [C, W, C] gather. The fixpoint IS the set of eligible vertices
    (all transitive deps committed), cycles included, so one pass
    executes exactly what ``TarjanDependencyGraph.execute()`` would (see
    ``tests/test_tpu_epaxos.py`` for the per-tick set equivalence).
  * Commit latency models the protocol phases: PreAccept out + PreAcceptOk
    back (one RTT) on the fast path, + Accept/AcceptOk (second RTT) on the
    slow path, sampled per instance (``epaxos/Replica.scala``
    handlePreAcceptOk). ``simplebpaxos=True`` adds the disaggregated
    proposer->depservice->acceptor hop of Simple BPaxos
    (``simplebpaxos/``), which costs one extra RTT before commit.
  * Cycles arise exactly as in the real protocol: two instances proposed
    concurrently in different columns can each include the other in their
    dependency snapshot (Bernoulli ``see_same_tick_rate``, quantized to
    16ths by the bit-sliced sampler), forming SCCs that the closure
    executes together.
  * ``general_deps=True`` switches the execute pass to TRUE EPaxos
    execution through the ``depgraph_execute`` kernel plane
    (:mod:`frankenpaxos_tpu.ops.depgraph`): at propose time the factored
    snapshot is MATERIALIZED into per-vertex adjacency rows of a packed
    ``[C*W, ceil(C*W/32)]`` bitmask (watermark edges to every live peer
    instance below the dependency watermark, plus the own-column chain
    bit), and eligibility/SCC condensation run as the plane's log-depth
    transitive closure instead of the factored greatest fixpoint. The
    two paths are state-equal tick for tick
    (``tests/test_tpu_epaxos.py``) — the factored fixpoint is the
    compressed special case — but the general path accepts NON-FACTORED
    dependency snapshots (arbitrary row edits), which the watermark
    encoding cannot represent.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    INF,
    LAT_BINS,
    sample_latency,
)
from frankenpaxos_tpu.ops import depgraph as depgraph_mod
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

_LANES = 32  # columns per packed visibility word


def _num_words(C: int) -> int:
    return -(-C // _LANES)


@dataclasses.dataclass(frozen=True)
class BatchedEPaxosConfig:
    """Static (compile-time) simulation parameters."""

    num_columns: int = 5  # C: instance leaders (BASELINE config 3 uses 5)
    window: int = 64  # W: in-flight instances per column (ring capacity)
    instances_per_tick: int = 2  # K: new proposals per column per tick
    lat_min: int = 1  # one-way message latency in ticks (uniform sample)
    lat_max: int = 3
    slow_path_rate: float = 0.2  # P(instance takes the Accept round trip)
    # P(a same-tick proposal in another column lands in the dependency
    # snapshot) — mutual visibility is what creates SCCs. Quantized to
    # multiples of 1/16 by the bit-sliced Bernoulli sampler.
    see_same_tick_rate: float = 0.5
    simplebpaxos: bool = False  # +1 RTT: proposer -> depservice -> acceptors
    # Unanimous BPaxos (unanimousbpaxos/Leader.scala fast/classic paths):
    # the leader takes the FAST path only when every dep-service node
    # reports the SAME dependency set — possible only when the instance
    # saw no same-tick concurrency, or when all nodes happened to observe
    # the concurrency identically (probability unanimity_rate). A failed
    # fast path falls back to a classic round: +1 RTT AND the dependency
    # set is widened to the UNION of node reports (here: every same-tick
    # peer — the superset the coordinator must adopt to be safe).
    # NOTE: unanimous_mode supersedes slow_path_rate (the fast/classic
    # decision is driven by unanimity, not the Bernoulli coin).
    unanimous_mode: bool = False
    unanimity_rate: float = 0.5  # P(nodes agree despite seen concurrency)
    # Closed workload: stop proposing once each column has allocated this
    # many instances (None = open workload).
    max_instances_per_column: Optional[int] = None
    # Frontier-history ring length H: an in-flight instance must execute
    # within H ticks of its proposal or the age_ok invariant trips (its
    # factored dependency row would be overwritten). Lifetimes are
    # commit latency + chain depth (tens of ticks); 256 is a wide margin.
    frontier_history: int = 256
    # Device-side GC / bounded state (simplegcbpaxos semantics:
    # GarbageCollector.scala:99-120 watermark broadcast,
    # Replica.scala:317-363 snapshots). When num_exec_replicas > 0, the
    # backend models R executing replicas whose per-column executed
    # watermarks lag the dep-graph pass; ring slots are pruned only
    # below the SNAPSHOT BARRIER (the quorum watermark captured by the
    # latest periodic snapshot), so state stays bounded exactly as far
    # as GC keeps up — and a crashed replica reviving behind the pruned
    # prefix recovers from the snapshot, not by replay. 0 = GC layer off
    # (slots prune the tick they execute).
    num_exec_replicas: int = 0  # R (use 2f+1-style odd counts)
    # TRUE EPaxos execution: materialize the factored snapshot into a
    # packed [C*W, ceil(C*W/32)] adjacency bitmask at propose time and
    # run the execute pass through the ``depgraph_execute`` kernel plane
    # (transitive closure + SCC condensation) instead of the factored
    # greatest fixpoint. Bit-identical state evolution to the factored
    # path (tests/test_tpu_epaxos.py), but the dependency snapshot is no
    # longer required to be watermark-shaped.
    general_deps: bool = False
    # Per-plane kernel dispatch policy (ops/registry.py) for the
    # depgraph_execute plane the general path runs through.
    kernels: KernelPolicy = KernelPolicy()
    replica_lag: int = 2  # mean ticks between a replica's watermark pulls
    rep_crash_rate: float = 0.0  # per-replica per-tick crash probability
    rep_revive_rate: float = 0.1  # per-crashed-replica revival probability
    snapshot_every: int = 32  # ticks between snapshot-barrier captures
    gc_quorum: int = 2  # replicas that must have executed before pruning
    # Unified in-graph fault injection (tpu/faults.py): the commit round
    # is modeled end-to-end (PreAccept/Accept RTTs), so drops/jitter
    # stretch it (TCP retransmit semantics) and a COLUMN-axis partition
    # defers cut columns' commits to the heal tick (their instances —
    # and every dependency chain through them — stall until then).
    # Crash/revive merges into the GC replica churn when that layer is
    # on. FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-column
    # instance admission (bounded by instances_per_tick per tick — the
    # fresh-visibility draw is K-shaped; the FIFO backlog carries the
    # rest). Completions are instance commits. WorkloadPlan.none() =
    # saturation.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def num_replicas(self) -> int:
        return self.num_columns

    def __post_init__(self):
        assert self.num_columns >= 2
        assert self.window >= 2 * self.instances_per_tick
        self.workload.validate()
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.slow_path_rate <= 1.0
        assert 0.0 <= self.see_same_tick_rate <= 1.0
        # The bit-sliced sampler quantizes to 16ths; a rate that silently
        # degrades to 0 or 1 would simulate a different protocol regime.
        k16 = round(self.see_same_tick_rate * 16)
        assert (k16 == 0) == (self.see_same_tick_rate == 0.0) and (
            k16 == 16
        ) == (self.see_same_tick_rate == 1.0), (
            f"see_same_tick_rate={self.see_same_tick_rate} quantizes to "
            f"{k16}/16; pick a multiple of 1/16 (or >= 1/32) instead"
        )
        assert self.frontier_history >= 8 * self.lat_max, (
            "frontier_history must comfortably exceed instance lifetimes"
        )
        assert 0.0 <= self.unanimity_rate <= 1.0
        if self.num_exec_replicas:
            assert 1 <= self.gc_quorum <= self.num_exec_replicas
            assert self.replica_lag >= 1
            assert self.snapshot_every >= 1
            assert 0.0 <= self.rep_crash_rate <= 1.0
            assert 0.0 <= self.rep_revive_rate <= 1.0
        self.kernels.validate()
        self.faults.validate(axis=self.num_columns)
        if self.faults.has_partition:
            # A cut column's instances commit only at the heal tick, and
            # their factored dependency rows must still be in the
            # frontier-history ring then (age_ok fails loudly otherwise).
            assert self.faults.partition_heal >= 0, (
                "epaxos needs a healing partition: a never-healing cut "
                "column outlives the frontier-history ring"
            )
            span = self.faults.partition_heal - self.faults.partition_start
            assert span + 8 * self.lat_max < self.frontier_history, (
                f"partition window {span} too long for "
                f"frontier_history={self.frontier_history}"
            )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedEPaxosState:
    """Struct-of-arrays instance state. Shapes: [C] columns, [C, W] ring
    instances, [C, W, CW] packed visibility bitmasks (CW = ceil(C/32)),
    [H, C] frontier history."""

    next_instance: jnp.ndarray  # [C] next per-column instance number
    head: jnp.ndarray  # [C] lowest non-executed per-column instance number
    # (execution is in column order, so head IS the executed watermark)

    proposed: jnp.ndarray  # [C, W] ring slot holds a live instance
    propose_tick: jnp.ndarray  # [C, W] proposal tick (INF = empty)
    commit_tick: jnp.ndarray  # [C, W] tick the commit lands (INF = empty)
    committed: jnp.ndarray  # [C, W] bool: commit has landed
    # Factored dependency snapshot: instance (c, i) at slot w depends on
    # fpre[propose_tick % H][e] of every column e, bumped to fpost[...][e]
    # where bit e of vis_bits[c, w] is set, and on all own predecessors.
    vis_bits: jnp.ndarray  # [C, W, CW] uint32 same-tick visibility mask
    fpre: jnp.ndarray  # [H, C] frontier BEFORE tick h's proposals
    fpost: jnp.ndarray  # [H, C] frontier AFTER tick h's proposals
    # Materialized adjacency for the general (non-factored) execute path:
    # [V, VW] uint32 with V = C*W ring-slot vertices (vertex = c*W + w)
    # and VW = ceil(V/32) packed dependency words per row. Zero-sized
    # when cfg.general_deps is off. Written only via jnp.where /
    # ops.depgraph helpers (the depgraph-containment lint keeps raw bit
    # twiddling of this leaf inside ops/depgraph.py).
    adj: jnp.ndarray  # [V, VW] uint32 (or [0, 0] when general_deps off)

    # GC layer (zero-width when cfg.num_exec_replicas == 0). With GC on,
    # ``head`` is the SNAPSHOT BARRIER (= prune watermark / ring base —
    # GC prunes exactly up to the latest periodic snapshot) while
    # ``exec_wm`` is the dep-graph execution watermark;
    # head <= quorum watermark <= exec_wm.
    exec_wm: jnp.ndarray  # [C] dep-graph executed watermark
    rep_exec: jnp.ndarray  # [R, C] per-replica executed watermark
    rep_down: jnp.ndarray  # [R] replica crashed
    snapshots_served: jnp.ndarray  # [] recoveries served from a snapshot
    rep_crashes: jnp.ndarray  # [] crash events (cumulative)

    # Stats.
    committed_total: jnp.ndarray  # [] cumulative commits
    fast_path_total: jnp.ndarray  # [] proposals that took the fast path
    executed_total: jnp.ndarray  # [] cumulative executions
    retired_total: jnp.ndarray  # [] cumulative retired (GC'd) instances
    coexecuted: jnp.ndarray  # [] executed in the same pass as one of its
    # dependencies (dependency chains committed together AND SCC members
    # both batch into one closure pass; true SCC detection is checked
    # against TarjanDependencyGraph in tests/test_tpu_epaxos.py)
    lat_sum: jnp.ndarray  # [] sum of propose->execute latencies
    lat_hist: jnp.ndarray  # [LAT_BINS] execute latency histogram
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedEPaxosConfig) -> BatchedEPaxosState:
    C, W, H = cfg.num_columns, cfg.window, cfg.frontier_history
    CW = _num_words(C)
    return BatchedEPaxosState(
        next_instance=jnp.zeros((C,), jnp.int32),
        head=jnp.zeros((C,), jnp.int32),
        proposed=jnp.zeros((C, W), bool),
        propose_tick=jnp.full((C, W), INF, jnp.int32),
        commit_tick=jnp.full((C, W), INF, jnp.int32),
        committed=jnp.zeros((C, W), bool),
        vis_bits=jnp.zeros((C, W, CW), jnp.uint32),
        fpre=jnp.zeros((H, C), jnp.int32),
        fpost=jnp.zeros((H, C), jnp.int32),
        adj=jnp.zeros(
            (C * W, depgraph_mod.num_words(C * W))
            if cfg.general_deps
            else (0, 0),
            jnp.uint32,
        ),
        exec_wm=jnp.zeros((C if cfg.num_exec_replicas else 0,), jnp.int32),
        rep_exec=jnp.zeros((cfg.num_exec_replicas, C), jnp.int32),
        rep_down=jnp.zeros((cfg.num_exec_replicas,), bool),
        snapshots_served=jnp.zeros((), jnp.int32),
        rep_crashes=jnp.zeros((), jnp.int32),
        committed_total=jnp.zeros((), jnp.int32),
        fast_path_total=jnp.zeros((), jnp.int32),
        executed_total=jnp.zeros((), jnp.int32),
        retired_total=jnp.zeros((), jnp.int32),
        coexecuted=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_columns, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def _pack_bool(b: jnp.ndarray) -> jnp.ndarray:
    """[..., C] bool -> [..., CW] uint32 (column e -> word e//32, lane
    e%32). The shared packing convention of vis_bits and the closure's
    bad-column masks."""
    C = b.shape[-1]
    CW = _num_words(C)
    pad = CW * _LANES - C
    if pad:
        b = jnp.concatenate(
            [b, jnp.zeros(b.shape[:-1] + (pad,), bool)], axis=-1
        )
    lanes = (
        jnp.uint32(1) << jnp.arange(_LANES, dtype=jnp.uint32)
    )
    words = b.reshape(b.shape[:-1] + (CW, _LANES))
    return jnp.sum(words.astype(jnp.uint32) * lanes, axis=-1)


def _bernoulli_words(
    key: jnp.ndarray, p: float, shape: Tuple[int, ...]
) -> jnp.ndarray:
    """Per-BIT Bernoulli(p) over packed uint32 words of the given shape,
    p quantized to k/16, via a bit-sliced 4-bit comparator (each of the 4
    random planes is one bit of a per-lane 4-bit value; lane set iff
    value < k). One random sweep of 4 words replaces 32 uniform draws."""
    k = int(round(p * 16))
    if k <= 0:
        return jnp.zeros(shape, jnp.uint32)
    if k >= 16:
        return jnp.full(shape, 0xFFFFFFFF, jnp.uint32)
    planes = jax.random.bits(key, (4,) + shape)  # uint32
    lt = jnp.zeros(shape, jnp.uint32)
    eq = jnp.full(shape, 0xFFFFFFFF, jnp.uint32)
    for i in (3, 2, 1, 0):  # MSB -> LSB of the 4-bit value
        b = planes[i]
        if (k >> i) & 1:
            lt = lt | (eq & ~b)
            eq = eq & b
        else:
            eq = eq & ~b
    return lt


def _tick_scores(
    m: jnp.ndarray, fpre: jnp.ndarray, fpost: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Score every history tick against the watermark vector ``m``:
    (ok_pre [H] — the tick's pre-frontier lies fully below m;
    bad_post [H, CW] — packed mask of columns whose post-frontier
    exceeds m). O(H*C)."""
    ok_pre = jnp.all(fpre <= m[None, :], axis=1)  # [H]
    bad_post = _pack_bool(fpost > m[None, :])  # [H, CW]
    return ok_pre, bad_post


def _instance_ok(
    ok_pre: jnp.ndarray,  # [H]
    bad_post: jnp.ndarray,  # [H, CW] — MUST be materialized (see note)
    h_idx: jnp.ndarray,  # [C, W] propose tick mod H (0 where empty)
    vis_bits: jnp.ndarray,  # [C, W, CW]
) -> jnp.ndarray:
    """[C, W] bool: the slot's dependency vector lies at or below the
    watermark the scores were computed for, for every PEER column
    (own-column order is enforced structurally by the contiguous-run
    scan). NOTE: callers must pass ``bad_post`` through a materialization
    point (a loop carry here) — XLA CPU otherwise fuses the packing
    reduction INTO the row gather and recomputes the 32-lane pack for
    every gathered element, a ~40x slowdown at C=1024."""
    okp = jnp.take(ok_pre, h_idx)  # [C, W]
    conflict = jnp.any(
        (vis_bits & jnp.take(bad_post, h_idx, axis=0)) != jnp.uint32(0),
        axis=2,
    )
    return okp & ~conflict


def eligible_closure(
    committed: jnp.ndarray,  # [C, W]
    proposed: jnp.ndarray,  # [C, W]
    propose_tick: jnp.ndarray,  # [C, W]
    vis_bits: jnp.ndarray,  # [C, W, CW]
    fpre: jnp.ndarray,  # [H, C]
    fpost: jnp.ndarray,  # [H, C]
    base: jnp.ndarray,  # [C] executed watermark the pass starts from
    next_instance: jnp.ndarray,  # [C]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The dependency-graph execute pass as a greatest fixpoint over
    per-column watermarks: the largest ``m`` (base <= m <= next_instance)
    such that every instance below ``m`` is committed and its dependency
    vector lies below ``m``. This is exactly the set of ELIGIBLE vertices
    of ``DependencyGraph.scala:8-125`` — vertices all of whose transitive
    dependencies are committed — including whole SCCs, which the
    reference executes together in one component.

    ``base`` is the ring head without the GC layer, or the execution
    watermark ``exec_wm`` with it (executed-but-unpruned slots then sit
    below base and fall outside the candidate window).

    Returns (newly [C, W] bool — slots to execute, run [C] — per-column
    executed count; base + run is the fixpoint watermark)."""
    C, W = committed.shape
    H = fpre.shape[0]
    w_iota = jnp.arange(W, dtype=jnp.int32)
    h_idx = jnp.where(proposed, jnp.mod(propose_tick, H), 0)
    ordinal = jnp.mod(w_iota[None, :] - base[:, None], W)  # [C, W]
    in_ring = ordinal < (next_instance - base)[:, None]
    cand = committed & proposed & in_ring
    pos_of_ord = jnp.mod(base[:, None] + w_iota[None, :], W)

    def run_of(ok_pre, bad_post):
        ok = _instance_ok(ok_pre, bad_post, h_idx, vis_bits) & cand
        ok_ord = jnp.take_along_axis(ok, pos_of_ord, axis=1)
        return jnp.sum(
            jnp.cumprod(ok_ord.astype(jnp.int32), axis=1), axis=1
        )

    # The tick scores ride the while-loop CARRY so the packed bad_post
    # mask is materialized at the loop boundary (see _instance_ok note).
    def body(carry):
        m, ok_pre, bad_post, _ = carry
        m_new = base + run_of(ok_pre, bad_post)
        ok_pre2, bad_post2 = _tick_scores(m_new, fpre, fpost)
        return m_new, ok_pre2, bad_post2, jnp.any(m_new != m)

    def cond(carry):
        return carry[3]

    # Start from the most permissive watermark; the update is monotone in
    # m, so iterating downward converges to the GREATEST fixpoint
    # (Tarski).
    ok_pre0, bad_post0 = _tick_scores(next_instance, fpre, fpost)
    m, _, _, _ = jax.lax.while_loop(
        cond, body, (next_instance, ok_pre0, bad_post0, jnp.bool_(True))
    )
    run = m - base
    newly = in_ring & (ordinal < run[:, None])
    return newly, run


def tick(
    cfg: BatchedEPaxosConfig,
    state: BatchedEPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedEPaxosState:
    """One simulation tick: commits land, the dependency-graph pass
    executes every eligible instance (SCCs included) and retires it, and
    columns propose new instances with PRNG-sampled factored dependency
    snapshots and commit latencies."""
    C, W, H = cfg.num_columns, cfg.window, cfg.frontier_history
    CW = _num_words(C)
    k_vis, k_slow, k_lat = jax.random.split(key, 3)
    w_iota = jnp.arange(W, dtype=jnp.int32)
    fp = cfg.faults  # unified fault plan (tpu/faults.py)
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)

    # ---- 1. Commits land (EpCommit arrival at the replica).
    landing = state.commit_tick <= t
    committed = state.committed | (state.proposed & landing)
    new_commit_mask = committed & ~state.committed
    n_new_commits = jnp.sum(new_commit_mask)

    # ---- 2. Dependency-graph execute pass (TarjanDependencyGraph
    # execute: all eligible vertices, SCCs together). Without the GC
    # layer the pass ALSO retires (head is the executed watermark); with
    # it, execution advances exec_wm and pruning waits for the quorum
    # watermark's snapshot barrier in step 2b.
    exec_base = state.exec_wm if cfg.num_exec_replicas else state.head
    if cfg.general_deps:
        # TRUE EPaxos execution: the eligible set comes from the
        # depgraph_execute plane's transitive closure over the
        # MATERIALIZED adjacency (written at propose time in step 3),
        # not from the factored fixpoint. Active = live and not yet
        # executed; executed-but-unpruned slots (GC layer) are inactive,
        # so their cleared-by-commitment rows never block a dependent.
        V = C * W
        abs_slot0 = state.head[:, None] + jnp.mod(
            w_iota[None, :] - state.head[:, None], W
        )
        active = state.proposed & (abs_slot0 >= exec_base[:, None])
        elig_b, _order_b, _root_b = ops_registry.dispatch(
            "depgraph_execute", cfg,
            state.adj[None],
            committed.reshape(1, V),
            active.reshape(1, V),
        )
        eligible = elig_b.reshape(C, W)
        # Own-column chain edges make per-column eligibility a prefix
        # from the execution watermark; the run length recovers the
        # factored path's watermark advance exactly.
        ordinal_e = jnp.mod(w_iota[None, :] - exec_base[:, None], W)
        in_ring_e = ordinal_e < (state.next_instance - exec_base)[:, None]
        pos_of_ord_e = jnp.mod(exec_base[:, None] + w_iota[None, :], W)
        elig_ord = jnp.take_along_axis(
            eligible & in_ring_e, pos_of_ord_e, axis=1
        )
        run = jnp.sum(
            jnp.cumprod(elig_ord.astype(jnp.int32), axis=1), axis=1
        )
        newly = in_ring_e & (ordinal_e < run[:, None])
    else:
        newly, run = eligible_closure(
            committed, state.proposed, state.propose_tick, state.vis_bits,
            state.fpre, state.fpost, exec_base, state.next_instance,
        )
    n_exec = jnp.sum(run)
    # Co-execution accounting: a newly executed instance whose deps were
    # not all executed BEFORE this pass (i.e. not a base instance with
    # its whole dependency vector already below the old watermarks)
    # executed together with at least one dependency — a same-pass chain
    # or SCC.
    ordinal = jnp.mod(w_iota[None, :] - exec_base[:, None], W)
    ok_pre_h, bad_post_h = _tick_scores(exec_base, state.fpre, state.fpost)
    # Only the base instance of a column can have had its whole
    # dependency vector below the old watermarks, so evaluate just that
    # one slot per column ([C, CW] work — no ring-wide gather).
    base_pos = jnp.mod(exec_base, W)  # [C]
    c_iota = jnp.arange(C, dtype=jnp.int32)
    h0 = jnp.where(
        state.proposed[c_iota, base_pos],
        jnp.mod(state.propose_tick[c_iota, base_pos], H),
        0,
    )  # [C]
    vis0 = state.vis_bits[c_iota, base_pos]  # [C, CW]
    conflict0 = jnp.any(
        (vis0 & jnp.take(bad_post_h, h0, axis=0)) != jnp.uint32(0), axis=1
    )
    ok0 = jnp.take(ok_pre_h, h0) & ~conflict0  # [C]
    dep_pre_ok = (ordinal == 0) & ok0[:, None]
    coexecuted = state.coexecuted + jnp.sum(newly & ~dep_pre_ok)
    lat = jnp.where(newly, t - state.propose_tick, 0)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    executed_total = state.executed_total + n_exec

    if cfg.num_exec_replicas:
        # ---- 2b. GC layer (simplegcbpaxos): executing replicas pull
        # the execution watermark with lag (and crash/revive); the
        # gc_quorum-th largest replica watermark is the quorum
        # watermark (GarbageCollector.scala:99-120 — prune only what a
        # quorum has executed); periodic snapshots capture it as the
        # SNAPSHOT BARRIER, and the ring prunes exactly to the barrier.
        # A live replica whose watermark fell below the pruned prefix
        # cannot replay it — it recovers from the snapshot
        # (Replica.scala:317-363), counted in snapshots_served.
        R = cfg.num_exec_replicas
        exec_wm = exec_base + run
        k_pull, k_crash, k_revive = jax.random.split(
            jax.random.fold_in(key, 1), 3
        )
        # A FaultPlan crash schedule composes with the native GC-replica
        # churn rates (identity under a none plan).
        eff_crash, eff_revive = faults_mod.effective_process_rates(
            fp, cfg.rep_crash_rate, cfg.rep_revive_rate, rates=frates
        )
        crash = ~state.rep_down & (
            jax.random.uniform(k_crash, (R,)) < eff_crash
        )
        revive = state.rep_down & (
            jax.random.uniform(k_revive, (R,)) < eff_revive
        )
        rep_down = (state.rep_down | crash) & ~revive
        rep_crashes = state.rep_crashes + jnp.sum(crash)
        quorum_wm = jnp.sort(state.rep_exec, axis=0)[
            R - cfg.gc_quorum
        ]  # [C]
        # Periodic snapshot at the quorum watermark; the barrier IS the
        # prune base (GC prunes exactly up to the latest snapshot).
        snap_now = jnp.mod(t, cfg.snapshot_every) == 0
        head = jnp.where(
            snap_now, jnp.maximum(state.head, quorum_wm), state.head
        )
        run_gc = head - state.head
        retired_total = state.retired_total + jnp.sum(run_gc)
        ordinal_h = jnp.mod(w_iota[None, :] - state.head[:, None], W)
        clear = ordinal_h < run_gc[:, None]  # pruned slots
        # Snapshot recovery FIRST: a live replica behind the pruned
        # prefix cannot replay it — it jumps to the snapshot barrier
        # (and only resumes ordinary replay next tick). Replay (the
        # watermark pull) is gated on NOT being lost: executing up to
        # exec_wm requires every instance from the replica's watermark
        # upward to still be in the ring.
        lost = ~rep_down[:, None] & (state.rep_exec < head[None, :])
        snapshots_served = state.snapshots_served + jnp.sum(
            jnp.any(lost, axis=1)
        )
        rep_exec = jnp.where(lost, head[None, :], state.rep_exec)
        pull = (
            (jax.random.uniform(k_pull, (R, C)) < 1.0 / cfg.replica_lag)
            & ~rep_down[:, None]
            & ~lost
        )
        rep_exec = jnp.where(pull, exec_wm[None, :], rep_exec)
    else:
        exec_wm = state.exec_wm  # zero-width
        rep_exec, rep_down = state.rep_exec, state.rep_down
        snapshots_served = state.snapshots_served
        rep_crashes = state.rep_crashes
        retired_total = state.retired_total + n_exec
        head = state.head + run
        clear = newly

    proposed = state.proposed & ~clear
    committed = committed & ~clear
    propose_tick = jnp.where(clear, INF, state.propose_tick)
    commit_tick = jnp.where(clear, INF, state.commit_tick)
    vis_bits = jnp.where(clear[:, :, None], jnp.uint32(0), state.vis_bits)
    if cfg.general_deps:
        # Retired vertices leave the graph entirely: rows AND columns
        # zeroed, so a ring slot reused by a later instance never
        # inherits stale incoming edges.
        adj = depgraph_mod.clear_vertices(state.adj, clear.reshape(C * W))
    else:
        adj = state.adj

    # ---- 3. Propose new instances (EpReplica handleClientRequest): up
    # to K per column if the window has room. The dependency snapshot is
    # factored: this tick's pre/post frontiers land in the history ring
    # at row t % H, and a bit-sliced Bernoulli decides which SAME-TICK
    # peer proposals are visible — mutual visibility creates cycles,
    # exactly like concurrent conflicting PreAccepts in the real
    # protocol. Own-column bits are masked off (own-column order is the
    # ring structure itself).
    space = W - (state.next_instance - head)
    # Workload admission (tpu/workload.py): the cap clamps the K
    # candidate slots per column (the fresh-visibility draw below is
    # K-shaped, so per-tick admission is bounded by instances_per_tick).
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, C)
        adm = workload_mod.admission(wl, wls, wl_writes)
        count = jnp.minimum(
            jnp.minimum(adm, cfg.instances_per_tick), space
        )
    else:
        count = jnp.minimum(cfg.instances_per_tick, space)
    if cfg.max_instances_per_column is not None:
        count = jnp.minimum(
            count,
            jnp.maximum(cfg.max_instances_per_column - state.next_instance, 0),
        )
    if wl.active:
        # Accounted AFTER every clamp: finish() must see the ACTUAL
        # per-column issue count, or the backlog drains entries the
        # ring never admitted.
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count,
            jnp.sum(new_commit_mask, axis=1),
        )
    delta = jnp.mod(w_iota[None, :] - state.next_instance[:, None], W)
    is_new = delta < count[:, None]
    next_instance = state.next_instance + count

    h_row = jnp.mod(t, H)
    fpre = state.fpre.at[h_row].set(state.next_instance)
    fpost = state.fpost.at[h_row].set(next_instance)

    # Fresh visibility bits only for the K new slots per column (the
    # full-ring draw would make threefry generation the dominant tick
    # cost at wide C), gathered back onto ring positions via delta.
    K = cfg.instances_per_tick
    if wl.has_conflict:
        # Traced conflict density (WorkloadState.conflict) overrides
        # the static see_same_tick_rate: [conflict x load] sweeps are
        # one compile. Same 4-plane bit-sliced comparator, so a traced
        # rate equal to the static one draws the identical stream.
        sees_k = depgraph_mod.bernoulli_words_k16(
            k_vis,
            workload_mod.conflict_k16(wl, wls, cfg.see_same_tick_rate),
            (C, K, CW),
        )
    else:
        sees_k = _bernoulli_words(
            k_vis, cfg.see_same_tick_rate, (C, K, CW)
        )
    col = jnp.arange(C, dtype=jnp.int32)
    own_mask = _pack_bool(col[:, None] == col[None, :])  # [C, CW]
    valid_mask = _pack_bool(jnp.ones((C,), bool))  # [CW] lanes < C
    sees_k = sees_k & ~own_mask[:, None, :] & valid_mask[None, None, :]
    if cfg.unanimous_mode:
        # Unanimous BPaxos fast/classic paths: seen concurrency breaks
        # dep-service unanimity with probability 1 - unanimity_rate; a
        # broken fast path widens the dependency set to the UNION (every
        # same-tick peer) and pays the classic round below.
        saw_any_k = jnp.any(sees_k != jnp.uint32(0), axis=2)  # [C, K]
        lucky_k = (
            jax.random.uniform(jax.random.fold_in(k_slow, 7), (C, K))
            < cfg.unanimity_rate
        )
        slow_k = saw_any_k & ~lucky_k
        full_k = ~own_mask[:, None, :] & valid_mask[None, None, :]
        sees_k = jnp.where(slow_k[:, :, None], full_k, sees_k)
    sees = jnp.take_along_axis(
        sees_k, jnp.clip(delta, 0, K - 1)[:, :, None], axis=1
    )  # [C, W, CW]
    vis_bits = jnp.where(is_new[:, :, None], sees, vis_bits)

    # Commit latency: PreAccept RTT (2 one-way hops), + Accept RTT on the
    # slow path, + the proposer->depservice hop pair for Simple BPaxos.
    hops = 2 + (2 if cfg.simplebpaxos else 0)
    rtt = jnp.sum(
        sample_latency(cfg.lat_min, cfg.lat_max, k_lat, (hops + 2, C, W)),
        axis=0,
    )  # [C, W]: hops+2 one-way samples; the last 2 are the slow path
    fast = jnp.sum(
        sample_latency(
            cfg.lat_min, cfg.lat_max, jax.random.fold_in(k_lat, 1),
            (hops, C, W),
        ),
        axis=0,
    )
    if cfg.unanimous_mode:
        slow = jnp.take_along_axis(
            slow_k, jnp.clip(delta, 0, K - 1), axis=1
        )  # [C, W]
    else:
        slow = jax.random.uniform(k_slow, (C, W)) < cfg.slow_path_rate
    fast_path_total = state.fast_path_total + jnp.sum(is_new & ~slow)
    commit_lat = jnp.where(slow, rtt, fast)
    # Unified fault injection: the commit round is modeled end-to-end,
    # so drops/jitter stretch it (TCP retransmit semantics) and a cut
    # column's commits defer to the partition's heal tick. none() skips
    # this at trace time.
    commit_arr = t + commit_lat
    if fp.traced or fp.drop_rate > 0.0 or fp.jitter > 0:
        commit_lat = faults_mod.tcp_latency(
            fp, faults_mod.fault_key(key), (C, W), commit_lat,
            rates=frates,
        )
        commit_arr = t + commit_lat
    if fp.has_partition:
        cut_col = (~faults_mod.partition_row(fp, t, C))[:, None]
        commit_arr = faults_mod.defer_to_heal(fp, commit_arr, cut_col)
    proposed = proposed | is_new
    propose_tick = jnp.where(is_new, t, propose_tick)
    commit_tick = jnp.where(is_new, commit_arr, commit_tick)
    committed = committed & ~is_new

    if cfg.general_deps:
        # Materialize the factored snapshot into adjacency rows for the
        # K candidate slots per column. The k-th new instance of column
        # c (abs = next_pre[c] + k) depends on every LIVE instance of
        # column e strictly below its dependency watermark d_e — the
        # pre-tick frontier, bumped to the post-tick frontier for the
        # peers its (post-widening) visibility draw saw — plus its
        # immediate own-column predecessor (chain bit), which carries
        # same-tick own-column ordering transitively. Edges to already
        # retired instances are simply absent (their vertices left the
        # graph); edges to executed-but-unpruned ones are satisfied by
        # inactivity in the plane.
        V = C * W
        K = cfg.instances_per_tick
        seen_k = depgraph_mod.unpack_mask(sees_k, C)  # [C, K, C] bool
        d = jnp.where(
            seen_k, next_instance[None, None, :],
            state.next_instance[None, None, :],
        )  # [C, K, C] per-peer dependency watermarks
        abs_after = head[:, None] + jnp.mod(
            w_iota[None, :] - head[:, None], W
        )  # [C, W] (post-clear base: pruned slots already excluded)
        dep_mask = (
            proposed[None, None, :, :]
            & (abs_after[None, None, :, :] < d[:, :, :, None])
        )  # [C, K, C, W]
        abs_new_k = state.next_instance[:, None] + jnp.arange(
            K, dtype=jnp.int32
        )  # [C, K]
        prev_id = (
            jnp.arange(C, dtype=jnp.int32)[:, None] * W
            + jnp.mod(abs_new_k - 1, W)
        )  # [C, K] vertex id of the immediate own-column predecessor
        chain_mask = (
            jnp.arange(V, dtype=jnp.int32)[None, None, :]
            == prev_id[:, :, None]
        ) & (abs_new_k - 1 >= head[:, None])[:, :, None]  # [C, K, V]
        rows_k = depgraph_mod.pack_mask(
            dep_mask.reshape(C, K, V) | chain_mask
        )  # [C, K, VW]
        rows = jnp.take_along_axis(
            rows_k, jnp.clip(delta, 0, K - 1)[:, :, None], axis=1
        )  # [C, W, VW]
        VW = rows.shape[-1]
        adj = jnp.where(
            is_new.reshape(V)[:, None], rows.reshape(V, VW), adj
        )

    # Telemetry: PreAccept fan-outs are the phase-2 plane; slow-path
    # Accept rounds show up as "retries" (the extra RTT the fast path
    # avoids); replica crash events land in leader_changes.
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase2_msgs=(C - 1) * jnp.sum(is_new),
        commits=n_new_commits,
        executes=n_exec,
        retries=jnp.sum(is_new & slow),
        leader_changes=rep_crashes - state.rep_crashes,
        queue_depth=jnp.sum(next_instance - head),
        queue_capacity=C * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    # Span sampler (telemetry.record_spans — the generic plumbing):
    # instance lifecycles on the per-column rings, from the masks this
    # tick already computed. Mapping: group = column, slot id = the
    # instance ordinal at each ring position (OLD head — valid for
    # every cell occupied at tick start, including this tick's GC
    # retirees); a cell proposed THIS tick carries the OLD
    # next_instance ordinal (retire + re-propose in one tick crosses a
    # full window). The PreAccept quorum and the commit are one event
    # in this model, so the vote and chosen stamps coincide; the
    # "executed" stamp is the ring retirement — the snapshot-barrier
    # prune under the GC layer, the execute pass itself without it.
    # No phase-1 plane: EPaxos is leaderless (nothing to promise).
    # Structurally OFF at spans=0, like the counter ring.
    if telemetry_mod.span_slots(tel):
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=is_new,
            slot_ids=state.head[:, None]
            + jnp.mod(w_iota[None, :] - state.head[:, None], W),
            new_slot_ids=state.next_instance[:, None] + delta,
            phase1_mark=jnp.zeros((C,), bool),
            voted=new_commit_mask,
            newly_chosen=new_commit_mask,
            retire_mask=clear,
        )

    return BatchedEPaxosState(
        next_instance=next_instance,
        head=head,
        proposed=proposed,
        propose_tick=propose_tick,
        commit_tick=commit_tick,
        committed=committed,
        vis_bits=vis_bits,
        fpre=fpre,
        fpost=fpost,
        adj=adj,
        exec_wm=exec_wm,
        rep_exec=rep_exec,
        rep_down=rep_down,
        snapshots_served=snapshots_served,
        rep_crashes=rep_crashes,
        committed_total=state.committed_total + n_new_commits,
        fast_path_total=fast_path_total,
        executed_total=executed_total,
        retired_total=retired_total,
        coexecuted=coexecuted,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedEPaxosConfig,
    state: BatchedEPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedEPaxosState, jnp.ndarray]:
    """Run ``num_ticks`` ticks under lax.scan; returns (state, t0+num_ticks)."""

    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedEPaxosConfig, state: BatchedEPaxosState, t
) -> dict:
    """Device-side safety checks; all returned booleans must be True."""
    # The execution counter is exactly the total watermark advance
    # (execution is in column order) — ties the cumulative stat to live
    # state, so a miscounted closure pass fails here.
    exec_base = state.exec_wm if cfg.num_exec_replicas else state.head
    conserved = state.executed_total == jnp.sum(exec_base)
    workload_ok = workload_mod.invariants_ok(
        cfg.workload, state.workload
    )
    books_ok = state.executed_total <= state.committed_total
    # Window bookkeeping: bounded state. With the GC layer this is THE
    # claim — the ring never outgrows W even though pruning waits for
    # the quorum watermark's snapshot barrier.
    window_ok = jnp.all(
        (state.head <= state.next_instance)
        & (state.next_instance - state.head <= cfg.window)
    )
    # Committed implies proposed (a commit can only land on a live slot).
    ring_ok = jnp.all(~state.committed | state.proposed)
    # Frontier-history residency: every live UNEXECUTED instance's
    # factored dependency row is still in the ring (age < H); executed
    # slots awaiting GC no longer need their row. A violation means
    # frontier_history is too small for this workload — fail LOUDLY.
    W = cfg.window
    w_iota = jnp.arange(W, dtype=jnp.int32)
    abs_slot = state.head[:, None] + jnp.mod(
        w_iota[None, :] - state.head[:, None], W
    )
    unexecuted = state.proposed & (abs_slot >= exec_base[:, None])
    age_ok = jnp.all(
        ~unexecuted | (t - state.propose_tick < cfg.frontier_history)
    )
    out = {
        "conserved": conserved,
        "workload_ok": workload_ok,
        "books_ok": books_ok,
        "window_ok": window_ok,
        "ring_ok": ring_ok,
        "age_ok": age_ok,
    }
    if cfg.num_exec_replicas:
        # GC ordering: prune base (= snapshot barrier) never passes the
        # execution watermark, and no replica is ever ahead of execution.
        out["gc_ok"] = jnp.all(state.head <= state.exec_wm) & jnp.all(
            state.rep_exec <= state.exec_wm[None, :]
        )
    if cfg.general_deps:
        # Dependency-graph safety: no executed instance has a remaining
        # edge to an unexecuted one (every dependency was executed with
        # or before it — retired deps' bits were cleared, executed-live
        # deps are themselves below the watermark); and vertices outside
        # the live ring carry no stale rows.
        V = cfg.num_columns * cfg.window
        exec_mask = (
            state.proposed & (abs_slot < exec_base[:, None])
        ).reshape(V)
        deps_ok = depgraph_mod.rows_subset(
            state.adj, depgraph_mod.pack_mask(exec_mask)
        )  # [V]
        rows_clear = jnp.all(
            jnp.where(
                state.proposed.reshape(V)[:, None],
                jnp.uint32(0),
                state.adj,
            )
            == jnp.uint32(0)
        )
        out["dep_safety_ok"] = (
            jnp.all(~exec_mask | deps_ok) & rows_clear
        )
    return out


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedEPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedEPaxosConfig(
        num_columns=5, window=32, instances_per_tick=2,
        num_exec_replicas=3, faults=faults, workload=workload,
    )


def analysis_config_general(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedEPaxosConfig:
    """The canonical small config for the GENERAL (materialized
    dependency-graph) execute path — same shape as
    :func:`analysis_config` with ``general_deps=True``, so the simtest
    registry exercises the ``depgraph_execute`` plane consumer under
    randomized fault/workload schedules."""
    return BatchedEPaxosConfig(
        num_columns=5, window=32, instances_per_tick=2,
        num_exec_replicas=3, general_deps=True,
        faults=faults, workload=workload,
    )
