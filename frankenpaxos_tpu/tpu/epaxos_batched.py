"""Batched EPaxos / Simple BPaxos as a single XLA program (BASELINE
config 3: dependency-graph protocols at scale).

The reference's hot loop for the EPaxos family is commit-then-execute
through a dependency graph: committed instances execute as eligible
strongly-connected components in reverse topological order
(``depgraph/TarjanDependencyGraph.scala:149``, ``epaxos/Replica.scala``).
Re-designed TPU-first:

  * ``C`` columns (one per replica/instance leader, the (replica, i)
    instance space of ``epaxos/Replica.scala``), each owning a ring of
    ``W`` in-flight instances — struct-of-arrays state, shardable over a
    device mesh along ``C``.
  * Dependency sets are PREFIX-SHAPED per column — exactly the
    ``InstancePrefixSet`` / top-k compression of the reference
    (``epaxos/InstancePrefixSet.scala``) — so an instance's deps are a
    single watermark vector ``dep[v] in Z^C``: v depends on every
    ``(d, j)`` with ``j < dep[v][d]``. Dependency checks become prefix-sum
    lookups instead of set operations.
  * The dependency-graph execute pass is an ELIGIBILITY CLOSURE computed
    with array ops: start from all committed-unexecuted instances and
    iteratively remove any whose dep watermark is not fully covered by
    (executed | candidate) — a per-column cumulative-sum plus gather,
    iterated under ``lax.while_loop`` to the greatest fixpoint. The fixed
    point IS the set of eligible vertices (all transitive deps committed),
    cycles included, so one pass executes exactly what
    ``TarjanDependencyGraph.execute()`` would (see
    ``tests/test_tpu_epaxos.py`` for the per-tick set equivalence).
  * Commit latency models the protocol phases: PreAccept out + PreAcceptOk
    back (one RTT) on the fast path, + Accept/AcceptOk (second RTT) on the
    slow path, sampled per instance (``epaxos/Replica.scala``
    handlePreAcceptOk). ``simplebpaxos=True`` adds the disaggregated
    proposer->depservice->acceptor hop of Simple BPaxos
    (``simplebpaxos/``), which costs one extra RTT before commit.
  * Cycles arise exactly as in the real protocol: two instances proposed
    concurrently in different columns can each include the other in their
    dependency snapshot (Bernoulli ``peer_visibility``), forming SCCs that
    the closure executes together.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    INF,
    LAT_BINS,
    ring_retire,
    sample_latency,
)


@dataclasses.dataclass(frozen=True)
class BatchedEPaxosConfig:
    """Static (compile-time) simulation parameters."""

    num_columns: int = 5  # C: instance leaders (BASELINE config 3 uses 5)
    window: int = 64  # W: in-flight instances per column (ring capacity)
    instances_per_tick: int = 2  # K: new proposals per column per tick
    lat_min: int = 1  # one-way message latency in ticks (uniform sample)
    lat_max: int = 3
    slow_path_rate: float = 0.2  # P(instance takes the Accept round trip)
    # P(a same-tick proposal in another column lands in the dependency
    # snapshot) — mutual visibility is what creates SCCs.
    see_same_tick_rate: float = 0.5
    simplebpaxos: bool = False  # +1 RTT: proposer -> depservice -> acceptors
    # Closed workload: stop proposing once each column has allocated this
    # many instances (None = open workload).
    max_instances_per_column: Optional[int] = None

    @property
    def num_replicas(self) -> int:
        return self.num_columns

    def __post_init__(self):
        assert self.num_columns >= 2
        assert self.window >= 2 * self.instances_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        assert 0.0 <= self.slow_path_rate <= 1.0
        assert 0.0 <= self.see_same_tick_rate <= 1.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedEPaxosState:
    """Struct-of-arrays instance state. Shapes: [C] columns, [C, W] ring
    instances, [C, W, C] per-instance dependency watermarks."""

    next_instance: jnp.ndarray  # [C] next per-column instance number
    head: jnp.ndarray  # [C] lowest non-retired per-column instance number

    proposed: jnp.ndarray  # [C, W] ring slot holds a live instance
    propose_tick: jnp.ndarray  # [C, W] proposal tick (INF = empty)
    commit_tick: jnp.ndarray  # [C, W] tick the commit lands (INF = empty)
    committed: jnp.ndarray  # [C, W] bool: commit has landed
    executed: jnp.ndarray  # [C, W] bool: executed by the dep-graph pass
    dep: jnp.ndarray  # [C, W, C] dependency watermarks (absolute indices)

    # Stats.
    committed_total: jnp.ndarray  # [] cumulative commits
    executed_total: jnp.ndarray  # [] cumulative executions
    retired_total: jnp.ndarray  # [] cumulative retired (GC'd) instances
    coexecuted: jnp.ndarray  # [] executed in the same pass as one of its
    # dependencies (dependency chains committed together AND SCC members
    # both batch into one closure pass; true SCC detection is checked
    # against TarjanDependencyGraph in tests/test_tpu_epaxos.py)
    lat_sum: jnp.ndarray  # [] sum of propose->execute latencies
    lat_hist: jnp.ndarray  # [LAT_BINS] execute latency histogram


def init_state(cfg: BatchedEPaxosConfig) -> BatchedEPaxosState:
    C, W = cfg.num_columns, cfg.window
    return BatchedEPaxosState(
        next_instance=jnp.zeros((C,), jnp.int32),
        head=jnp.zeros((C,), jnp.int32),
        proposed=jnp.zeros((C, W), bool),
        propose_tick=jnp.full((C, W), INF, jnp.int32),
        commit_tick=jnp.full((C, W), INF, jnp.int32),
        committed=jnp.zeros((C, W), bool),
        executed=jnp.zeros((C, W), bool),
        dep=jnp.zeros((C, W, C), jnp.int32),
        committed_total=jnp.zeros((), jnp.int32),
        executed_total=jnp.zeros((), jnp.int32),
        retired_total=jnp.zeros((), jnp.int32),
        coexecuted=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
    )


def _prefix_counts(bm: jnp.ndarray, head: jnp.ndarray) -> jnp.ndarray:
    """P[c, r] = how many of column c's first r in-ring instances (in
    absolute order from head) are set in ``bm``. Shape [C, W+1]."""
    C, W = bm.shape
    w_iota = jnp.arange(W, dtype=jnp.int32)
    pos_of_ord = (head[:, None] + w_iota[None, :]) % W
    bm_ord = jnp.take_along_axis(bm, pos_of_ord, axis=1).astype(jnp.int32)
    cum = jnp.cumsum(bm_ord, axis=1)
    return jnp.concatenate([jnp.zeros((C, 1), jnp.int32), cum], axis=1)


def _deps_satisfied_by(
    dep: jnp.ndarray,  # [C, W, C] absolute watermarks
    base: jnp.ndarray,  # [C, W] bool: instances counted as executed
    head: jnp.ndarray,  # [C]
) -> jnp.ndarray:
    """[C, W] bool: every dependency of the slot's instance is in ``base``
    (instances below head count as executed — they retired)."""
    C, W = base.shape
    P = _prefix_counts(base, head)  # [C, W+1]
    r = jnp.clip(dep - head[None, None, :], 0, W)  # [C, W, C] relative
    gathered = P[jnp.arange(C)[None, None, :], r]  # [C, W, C]
    return jnp.all((r <= 0) | (gathered == r), axis=2)


def eligible_closure(
    committed: jnp.ndarray,  # [C, W]
    executed: jnp.ndarray,  # [C, W]
    dep: jnp.ndarray,  # [C, W, C]
    head: jnp.ndarray,  # [C]
) -> jnp.ndarray:
    """The dependency-graph execute pass as a greatest fixpoint: the
    largest set E of committed-unexecuted instances whose dependencies all
    lie in (executed | E). This is exactly the set of ELIGIBLE vertices of
    ``DependencyGraph.scala:8-125`` — vertices all of whose transitive
    dependencies are committed — including whole SCCs, which the reference
    executes together in one component."""

    def body(carry):
        E, _ = carry
        ok = _deps_satisfied_by(dep, executed | E, head)
        newE = E & ok
        return newE, jnp.any(newE != E)

    def cond(carry):
        return carry[1]

    E0 = committed & ~executed
    E, _ = jax.lax.while_loop(cond, body, (E0, jnp.bool_(True)))
    return E


def tick(
    cfg: BatchedEPaxosConfig,
    state: BatchedEPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedEPaxosState:
    """One simulation tick: commits land, the dependency-graph pass
    executes every eligible instance (SCCs included), fully-executed
    column prefixes retire, and columns propose new instances with
    PRNG-sampled dependency snapshots and commit latencies."""
    C, W = cfg.num_columns, cfg.window
    k_vis, k_slow, k_lat = jax.random.split(key, 3)
    w_iota = jnp.arange(W, dtype=jnp.int32)

    # ---- 1. Commits land (EpCommit arrival at the replica).
    landing = state.commit_tick <= t
    committed = state.committed | (state.proposed & landing)
    n_new_commits = jnp.sum(committed & ~state.committed)

    # ---- 2. Dependency-graph execute pass (TarjanDependencyGraph
    # execute: all eligible vertices, SCCs together).
    newly = eligible_closure(committed, state.executed, state.dep, state.head)
    executed = state.executed | newly
    # Co-execution accounting: a newly executed instance whose deps were
    # not all executed BEFORE this pass executed together with at least
    # one dependency (a same-pass chain or an SCC).
    dep_pre_ok = _deps_satisfied_by(state.dep, state.executed, state.head)
    coexecuted = state.coexecuted + jnp.sum(newly & ~dep_pre_ok)
    lat = jnp.where(newly, t - state.propose_tick, 0)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    executed_total = state.executed_total + jnp.sum(newly)

    # ---- 3. Retire the contiguous executed prefix of each column (the
    # ring GC; executed-out-of-order instances wait for their column hole).
    pos_of_ord = (state.head[:, None] + w_iota[None, :]) % W
    exec_ord = jnp.take_along_axis(executed, pos_of_ord, axis=1)
    in_ring = w_iota[None, :] < (state.next_instance - state.head)[:, None]
    retire_ord = exec_ord & in_ring
    n_retire, retire_mask = ring_retire(retire_ord, state.head)
    head = state.head + n_retire
    retired_total = state.retired_total + jnp.sum(n_retire)

    proposed = state.proposed & ~retire_mask
    committed = committed & ~retire_mask
    executed = executed & ~retire_mask
    propose_tick = jnp.where(retire_mask, INF, state.propose_tick)
    commit_tick = jnp.where(retire_mask, INF, state.commit_tick)

    # ---- 4. Propose new instances (EpReplica handleClientRequest): up to
    # K per column if the window has room. The dependency snapshot is the
    # per-column proposal frontier; a Bernoulli per (instance, column)
    # decides whether SAME-TICK proposals of other columns are visible —
    # mutual visibility creates cycles, exactly like concurrent
    # conflicting PreAccepts in the real protocol.
    space = W - (state.next_instance - head)
    count = jnp.minimum(cfg.instances_per_tick, space)
    if cfg.max_instances_per_column is not None:
        count = jnp.minimum(
            count, jnp.maximum(cfg.max_instances_per_column - state.next_instance, 0)
        )
    delta = (w_iota[None, :] - state.next_instance[:, None]) % W
    is_new = delta < count[:, None]
    next_instance = state.next_instance + count

    # Dependency watermarks: before-this-tick frontier of every column,
    # optionally extended to the after-this-tick frontier of OTHER columns
    # (same-tick visibility); own column = own index (a replica serializes
    # its own instances, InstanceHelpers/own-column conflicts).
    own_index = state.next_instance[:, None] + delta  # [C, W] absolute
    base_frontier = state.next_instance[None, None, :]  # [1, 1, C] pre-tick
    after_frontier = next_instance[None, None, :]  # [1, 1, C] post-tick
    sees = (
        jax.random.uniform(k_vis, (C, W, C)) < cfg.see_same_tick_rate
        if cfg.see_same_tick_rate > 0.0
        else jnp.zeros((C, W, C), bool)
    )
    dep_new = jnp.where(sees, after_frontier, base_frontier)
    dep_new = jnp.broadcast_to(dep_new, (C, W, C))
    own_col = jnp.arange(C)[:, None, None] == jnp.arange(C)[None, None, :]
    dep_new = jnp.where(own_col, own_index[:, :, None], dep_new)
    dep = jnp.where(is_new[:, :, None], dep_new, state.dep)

    # Commit latency: PreAccept RTT (2 one-way hops), + Accept RTT on the
    # slow path, + the proposer->depservice hop pair for Simple BPaxos.
    hops = 2 + (2 if cfg.simplebpaxos else 0)
    rtt = jnp.sum(
        sample_latency(cfg.lat_min, cfg.lat_max, k_lat, (hops + 2, C, W)), axis=0
    )  # [C, W]: hops+2 one-way samples; the last 2 are the slow path
    fast = jnp.sum(
        sample_latency(cfg.lat_min, cfg.lat_max, jax.random.fold_in(k_lat, 1), (hops, C, W)), axis=0
    )
    slow = jax.random.uniform(k_slow, (C, W)) < cfg.slow_path_rate
    commit_lat = jnp.where(slow, rtt, fast)
    proposed = proposed | is_new
    propose_tick = jnp.where(is_new, t, propose_tick)
    commit_tick = jnp.where(is_new, t + commit_lat, commit_tick)

    return BatchedEPaxosState(
        next_instance=next_instance,
        head=head,
        proposed=proposed,
        propose_tick=propose_tick,
        commit_tick=commit_tick,
        committed=committed,
        executed=executed,
        dep=dep,
        committed_total=state.committed_total + n_new_commits,
        executed_total=executed_total,
        retired_total=retired_total,
        coexecuted=coexecuted,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
    )


@functools.partial(jax.jit, static_argnums=(0, 3))
def run_ticks(
    cfg: BatchedEPaxosConfig,
    state: BatchedEPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedEPaxosState, jnp.ndarray]:
    """Run ``num_ticks`` ticks under lax.scan; returns (state, t0+num_ticks)."""

    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedEPaxosConfig, state: BatchedEPaxosState, t
) -> dict:
    """Device-side safety checks; all returned booleans must be True."""
    # Executed implies committed (only committed vertices are eligible,
    # DependencyGraph.scala:8-125).
    exec_committed = jnp.all(~state.executed | state.committed)
    # Every executed instance's dependencies are executed or retired (the
    # closure never executes a vertex whose deps aren't in the closure).
    deps_ok = jnp.all(
        ~state.executed
        | _deps_satisfied_by(state.dep, state.executed, state.head)
    )
    # Window bookkeeping.
    window_ok = jnp.all(
        (state.head <= state.next_instance)
        & (state.next_instance - state.head <= cfg.window)
    )
    # Conservation: everything retired was executed first.
    conserved = state.retired_total <= state.executed_total
    return {
        "exec_committed": exec_committed,
        "deps_ok": deps_ok,
        "window_ok": window_ok,
        "conserved": conserved,
    }
