"""Device-side telemetry: per-tick metric rings that live INSIDE the
compiled ``lax.scan``.

The only visibility into a compiled tick loop used to be host-side
``stats()`` pulls between ``run()`` segments — the loop itself was a
black box. Compartmentalized MultiPaxos (PAPERS: arxiv 2012.15762) makes
the case that *finding the bottleneck component is the optimization
method* for SMR; that needs per-phase counters with per-tick resolution,
not end-of-segment totals. This module is the repo-wide contract for
that: one metrics struct, one ring-buffer idiom, one exposition format.

Design:

  * :class:`Telemetry` is a pytree carried in every batched backend's
    ``*State`` dataclass, so it threads through ``run_ticks``'s scan
    carry (and through donation, sharding, vmap, and ``widen_state``)
    with no signature changes anywhere.
  * Each ``tick`` calls :func:`record` with per-tick event counts that
    the tick has ALREADY computed for its own bookkeeping (quorum sums,
    retire counts, cumulative-counter deltas) — int32 adds on values
    resident in registers, plus ONE dynamic-update-slice of a
    ``[NUM_COLS]`` row into the ``[K, NUM_COLS]`` ring per tick. All
    leaves are int32 (the dtype policy's accumulator width), so
    ``widen_state`` is a no-op and narrowed/widened runs stay
    bit-identical.
  * The ring keeps the last ``K`` ticks (slot ``= ticks % K``), so a
    single coalesced ``jax.device_get`` at an epoch boundary yields a
    full per-tick time series with zero host sync inside the hot loop
    (the pull itself still waits for in-flight device work, like any
    transfer — the point is the LOOP never syncs).
    Ring contents are invariant to K where windows overlap: the value
    recorded for tick t is the same regardless of window size.
  * ``window = 0`` disables telemetry STRUCTURALLY: :func:`record`
    no-ops at trace time (K is a static shape), so XLA dead-code
    eliminates every count that feeds only telemetry — the zero-overhead
    baseline the ``bench.py --telemetry`` budget check compares against.

Exposition naming scheme (host + device metrics unify under it):
``fpx_device_*`` for in-graph metrics (this module), ``fpx_host_*`` for
transport-level trace spans; counters end in ``_total``, histograms use
Prometheus cumulative ``_bucket{le=...}`` lines. Rendered by
:func:`exposition_lines` and consumed by ``monitoring/scrape.py`` /
``monitoring/dashboard.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import LAT_BINS

# Per-tick ring columns. All but queue_depth are event counters (events
# that happened THIS tick — rotations counts tpu/lifecycle.py window
# rolls, resizes counts tpu/elastic.py applied role-count changes);
# queue_depth is a gauge sampled at tick end (in-flight work
# items — ring occupancy / window backlog, per backend).
COUNTER_FIELDS = (
    "proposals",
    "phase1_msgs",
    "phase2_msgs",
    "commits",
    "executes",
    "drops",
    "retries",
    "leader_changes",
    "rotations",
    "resizes",
    "queue_depth",
)
NUM_COLS = len(COUNTER_FIELDS)
COL = {name: i for i, name in enumerate(COUNTER_FIELDS)}

TELEM_WINDOW = 128  # default ring size K (ticks)
QUEUE_BINS = 32  # queue-depth histogram bins (occupancy fractions)

# -- Span sampler (the serve loop's device-side lifecycle tracer) -----------
# A reservoir of S sampled in-flight slots whose lifecycle tick-stamps
# are recorded INSIDE the tick (reusing the masks the tick already
# computes); completed spans roll into a completion ring the host
# drains with a cursor, exactly like the counter ring. ``spans=0``
# (the default) zero-sizes every leaf — a structural no-op, like
# ``window=0`` for the counters.
SPAN_STAGES = (
    "proposed",
    "phase1_promised",
    "phase2_voted",
    "committed",
    "executed",
)
NUM_STAGES = len(SPAN_STAGES)
# Completion-ring columns: identity (group, per-group slot id) + the
# five stage stamps.
SPAN_COLS = ("group", "slot_id") + SPAN_STAGES
NUM_SPAN_COLS = len(SPAN_COLS)
SPAN_RING_FACTOR = 8  # completion-ring rows per reservoir slot
NO_STAMP = -1  # unstamped stage marker


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Telemetry:
    """Device-resident metric ring. All leaves int32 (accumulator width
    under the dtype policy — never narrowed, never widened; x64 is off
    in this runtime). ``totals`` therefore wraps mod 2^32 on very long
    runs — the busiest flagship column (phase2_msgs, ~50k/tick) wraps
    after ~80k ticks, ~10x a full bench.py run. Host-side views
    (:func:`summary`, :func:`exposition_lines`) reinterpret the totals
    as unsigned so a wrapped counter reads as a Prometheus counter
    reset (which ``rate()`` handles), never as a negative sample."""

    ticks: jnp.ndarray  # [] ticks recorded since creation
    counters: jnp.ndarray  # [K, NUM_COLS] per-tick ring (slot = t % K)
    totals: jnp.ndarray  # [NUM_COLS] cumulative sums of every column
    lat_hist: jnp.ndarray  # [LAT_BINS] commit-latency histogram (ticks)
    queue_hist: jnp.ndarray  # [QUEUE_BINS] occupancy-fraction histogram
    # Span sampler (all zero-sized when spans == 0): the live reservoir
    # tracks (group, ring position, per-group slot id, stage stamps);
    # completed spans roll into span_ring (slot = spans_done % SR).
    span_group: jnp.ndarray  # [S] tracked group (-1 = slot free)
    span_pos: jnp.ndarray  # [S] ring position of the tracked slot
    span_id: jnp.ndarray  # [S] per-group slot sequence number
    span_t: jnp.ndarray  # [S, NUM_STAGES] stage tick stamps (NO_STAMP)
    span_ring: jnp.ndarray  # [SR, NUM_SPAN_COLS] completed-span ring
    spans_done: jnp.ndarray  # [] completed spans (cumulative)


def make_telemetry(
    window: int = TELEM_WINDOW, spans: int = 0
) -> Telemetry:
    """A zeroed telemetry ring of ``window`` ticks; ``window=0`` turns
    the subsystem off structurally (record() becomes a trace-time
    no-op and XLA removes the feeding computations). ``spans`` is the
    span-sampler reservoir size (``spans=0`` — the default — disables
    the sampler structurally the same way)."""
    assert window >= 0 and spans >= 0
    SR = spans * SPAN_RING_FACTOR
    return Telemetry(
        ticks=jnp.zeros((), jnp.int32),
        counters=jnp.zeros((window, NUM_COLS), jnp.int32),
        totals=jnp.zeros((NUM_COLS,), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        queue_hist=jnp.zeros((QUEUE_BINS,), jnp.int32),
        span_group=jnp.full((spans,), -1, jnp.int32),
        span_pos=jnp.zeros((spans,), jnp.int32),
        span_id=jnp.full((spans,), -1, jnp.int32),
        span_t=jnp.full((spans, NUM_STAGES), NO_STAMP, jnp.int32),
        span_ring=jnp.full((SR, NUM_SPAN_COLS), NO_STAMP, jnp.int32),
        spans_done=jnp.zeros((), jnp.int32),
    )


def window(tel: Telemetry) -> int:
    """The ring size K — a static shape, readable at trace time."""
    return tel.counters.shape[0]


def span_slots(tel: Telemetry) -> int:
    """The span-sampler reservoir size S — a static shape. 0 = the
    sampler is off structurally (record_spans no-ops at trace time)."""
    return tel.span_group.shape[0]


def record(
    tel: Telemetry,
    *,
    proposals=0,
    phase1_msgs=0,
    phase2_msgs=0,
    commits=0,
    executes=0,
    drops=0,
    retries=0,
    leader_changes=0,
    rotations=0,
    resizes=0,
    queue_depth=0,
    queue_capacity: int = 0,
    lat_hist_delta: Optional[jnp.ndarray] = None,
) -> Telemetry:
    """Record one tick. Counter args are this tick's event counts
    (scalars, traced or Python ints); ``queue_depth`` is the end-of-tick
    backlog gauge, binned into ``queue_hist`` as a fraction of the
    static ``queue_capacity`` (0 = don't bin). ``lat_hist_delta`` is
    this tick's [LAT_BINS] commit-latency increment (most backends
    already compute it as a ``segment_sum``; pass the same array).

    With a zero-width ring this is a trace-time no-op except the tick
    count — the disabled-telemetry baseline costs nothing."""
    ticks = tel.ticks + 1
    if window(tel) == 0:
        return dataclasses.replace(tel, ticks=ticks)
    row = jnp.stack(
        [
            jnp.asarray(v, jnp.int32).reshape(())
            for v in (
                proposals,
                phase1_msgs,
                phase2_msgs,
                commits,
                executes,
                drops,
                retries,
                leader_changes,
                rotations,
                resizes,
                queue_depth,
            )
        ]
    )
    slot = jnp.mod(tel.ticks, window(tel))
    counters = jax.lax.dynamic_update_slice(
        tel.counters, row[None, :], (slot, jnp.int32(0))
    )
    lat_hist = tel.lat_hist
    if lat_hist_delta is not None:
        lat_hist = lat_hist + lat_hist_delta.astype(jnp.int32)
    queue_hist = tel.queue_hist
    if queue_capacity > 0:
        qbin = jnp.clip(
            jnp.asarray(queue_depth, jnp.int32) * QUEUE_BINS
            // jnp.int32(queue_capacity),
            0,
            QUEUE_BINS - 1,
        )
        queue_hist = queue_hist.at[qbin].add(1)
    return dataclasses.replace(
        tel,
        ticks=ticks,
        counters=counters,
        totals=tel.totals + row,
        lat_hist=lat_hist,
        queue_hist=queue_hist,
    )


def record_spans(
    tel: Telemetry,
    *,
    t,
    is_new: jnp.ndarray,
    slot_ids: jnp.ndarray,
    new_slot_ids: Optional[jnp.ndarray] = None,
    phase1_mark: jnp.ndarray,
    voted: jnp.ndarray,
    newly_chosen: jnp.ndarray,
    retire_mask: jnp.ndarray,
) -> Telemetry:
    """One tick of the in-graph span sampler. All mask args are the
    ``[G, W]`` masks the tick already computed for its own bookkeeping
    (``is_new`` = newly proposed, ``voted`` = a Phase2b vote is visible
    at the counter, ``newly_chosen`` / ``retire_mask`` = the dispatch
    plane's outputs); ``slot_ids`` is the per-group slot number at each
    ring position (OLD head + ordinal — valid for every cell that was
    occupied at tick START, including cells retiring this tick);
    ``new_slot_ids`` is the slot number a cell proposed THIS tick
    carries (OLD next_slot + ordinal — a cell can retire and be
    re-proposed in one tick, in which case its new slot is one full
    window past the old-head formula; defaults to ``slot_ids`` for
    backends where the two never diverge). ``phase1_mark`` is the
    ``[G]`` mask of groups the phase-1 plane touched this tick
    (election or reconfiguration repair).

    Per tick: at most ONE new span is adopted (the first ``is_new``
    cell into the first free reservoir slot — a cheap deterministic
    reservoir; serve-loop chunks are long enough that the reservoir
    samples continuously), live spans gather their cell's masks and
    stamp each stage's FIRST occurrence, and spans whose slot retires
    roll into the completion ring (slot = spans_done % SR) and free
    their reservoir entry. With ``spans == 0`` this is a trace-time
    no-op (the structural-disable contract of the counter ring)."""
    S = span_slots(tel)
    if S == 0:
        return tel
    G, W = is_new.shape
    SR = tel.span_ring.shape[0]
    t32 = jnp.asarray(t, jnp.int32)
    s_iota = jnp.arange(S, dtype=jnp.int32)

    # -- adopt: first free reservoir slot takes one new proposal. The
    # group scan start rotates per tick so the reservoir samples across
    # the whole group axis, not just group 0's hot cell. Cost: ONE
    # [G, W] any-reduction plus [G]/[W]-sized bookkeeping per tick —
    # never a [G*W]-wide argmax (which would be visible tick work at
    # flagship shapes).
    any_new = jnp.any(is_new, axis=1)  # [G]
    g_off = jnp.mod(t32, G)
    g_new = jnp.mod(
        jnp.argmax(jnp.roll(any_new, -g_off)).astype(jnp.int32) + g_off,
        G,
    )  # a group with a new proposal (0 if none — gated below)
    w_new = jnp.argmax(is_new[g_new]).astype(jnp.int32)
    free = tel.span_group < 0
    adopt = jnp.any(any_new) & jnp.any(free)
    adopt_s = (s_iota == jnp.argmax(free)) & adopt  # [S] one-hot
    id_new = (
        new_slot_ids if new_slot_ids is not None else slot_ids
    )[g_new, w_new]

    # -- stamp live spans (pre-adopt occupancy: a span adopted this
    # tick gets only its "proposed" stamp below; latencies are >= 1
    # tick so no later stage can fire the same tick it was proposed).
    occ = tel.span_group >= 0
    gg = jnp.clip(tel.span_group, 0, G - 1)
    ww = jnp.clip(tel.span_pos, 0, W - 1)

    def gat(arr2d):
        return arr2d[gg, ww]

    match = occ & (gat(slot_ids) == tel.span_id)
    stamps = jnp.stack(
        [
            jnp.zeros((S,), bool),  # proposed: stamped at adoption
            match & phase1_mark[gg],
            match & gat(voted),
            match & gat(newly_chosen),
            match & gat(retire_mask),
        ],
        axis=1,
    )  # [S, NUM_STAGES]
    span_t = jnp.where(
        stamps & (tel.span_t == NO_STAMP), t32, tel.span_t
    )
    span_t = jnp.where(
        adopt_s[:, None] & (jnp.arange(NUM_STAGES) == 0)[None, :],
        t32,
        span_t,
    )
    span_group = jnp.where(adopt_s, g_new, tel.span_group)
    span_pos = jnp.where(adopt_s, w_new, tel.span_pos)
    span_id = jnp.where(adopt_s, id_new, tel.span_id)

    # -- complete: spans whose slot retired this tick roll into the
    # completion ring and free their reservoir entry. mode="drop"
    # parks non-completing rows at the out-of-range index SR.
    done = match & gat(retire_mask)
    rank = jnp.cumsum(done.astype(jnp.int32)) - 1  # [S]
    ring_slot = jnp.where(
        done, (tel.spans_done + rank) % SR, SR
    )
    rows = jnp.concatenate(
        [span_group[:, None], span_id[:, None], span_t], axis=1
    )  # [S, NUM_SPAN_COLS]
    span_ring = tel.span_ring.at[ring_slot].set(rows, mode="drop")
    spans_done = tel.spans_done + jnp.sum(done)
    span_group = jnp.where(done, -1, span_group)
    span_id = jnp.where(done, -1, span_id)
    span_t = jnp.where(done[:, None], NO_STAMP, span_t)
    return dataclasses.replace(
        tel,
        span_group=span_group,
        span_pos=span_pos,
        span_id=span_id,
        span_t=span_t,
        span_ring=span_ring,
        spans_done=spans_done,
    )


# ---------------------------------------------------------------------------
# Fleet axis: per-instance views + the in-graph summary reduction.
# ---------------------------------------------------------------------------
# A FLEET telemetry pytree is an ordinary Telemetry whose every leaf
# carries one LEADING instance axis ([F], [F, K, cols], ...) — exactly
# what ``parallel.sharding.fleet_states`` broadcasts and
# ``run_ticks_fleet`` carries through the vmapped scan. Host drains
# slice it per instance (:func:`instance_view`) so every single-
# instance code path below works unchanged; the in-graph
# :func:`fleet_summary` reduces each instance's ring window to a small
# fixed summary vector + a straggler flag, so a fleet serve loop can
# pull O(F) scalars per chunk instead of F full rings.

# Columns of the per-instance summary vector ``fleet_summary`` emits.
# All int32 (commit rate in x1000 fixed point), so summaries are
# bit-deterministic across hosts and mesh shapes.
FLEET_SUMMARY_COLS = (
    "ticks",  # cumulative ticks recorded
    "window_ticks",  # ring window the rates below cover (min(ticks, K))
    "commits",  # commits in the window
    "commit_rate_x1000",  # commits/tick over the window, x1000
    "rotations",  # lifecycle window rolls in the window
    "p50_commit_latency",  # cumulative-hist percentiles (bins; -1 empty)
    "p99_commit_latency",
    "p50_queue_wait",
    "p99_queue_wait",
    "shed",  # cumulative arrivals shed (0 when unshaped)
    "straggler",  # 1 = flagged by the in-graph outlier test
)
NUM_SUMMARY_COLS = len(FLEET_SUMMARY_COLS)
SUMMARY_COL = {name: i for i, name in enumerate(FLEET_SUMMARY_COLS)}


def is_fleet(tel: Telemetry) -> bool:
    """True when the telemetry carries a leading instance axis (the
    fleet-state layout: ``ticks`` is [F] instead of a scalar)."""
    return jnp.ndim(tel.ticks) == 1


def fleet_size_of(tel: Telemetry) -> int:
    assert is_fleet(tel), "not a fleet telemetry (scalar ticks)"
    return tel.ticks.shape[0]


def instance_view(tel: Telemetry, i: int) -> Telemetry:
    """Instance ``i``'s slice of a fleet telemetry — shaped exactly
    like a single-instance Telemetry, so every host view (series /
    summary / DrainCursor) applies unchanged. Works on a fetched
    (numpy) or device-resident pytree."""
    return jax.tree_util.tree_map(lambda a: a[i], tel)


def _hist_percentile_rows(hist, q_num: int, q_den: int):
    """Nearest-rank percentile per ROW of an integer histogram batch
    ``[F, B]`` (bin index = value), in-graph: ``ceil(q * total)`` rank,
    -1 on an empty row. Overflow-safe split ceil (totals * q_num can
    pass int32 on long runs)."""
    F = hist.shape[0]
    if hist.ndim != 2 or hist.shape[1] == 0:
        return jnp.full((F,), -1, jnp.int32)
    h = hist.astype(jnp.int32)
    total = jnp.sum(h, axis=1)
    # ceil(total * q_num / q_den) without the int32 overflow of the
    # naive product: total = a * q_den + b.
    a, b = total // q_den, total % q_den
    rank = jnp.maximum(1, a * q_num + (b * q_num + q_den - 1) // q_den)
    cum = jnp.cumsum(h, axis=1)
    idx = jnp.argmax(cum >= rank[:, None], axis=1).astype(jnp.int32)
    return jnp.where(total > 0, idx, -1)


def _int_median(x):
    """Lower median of an int32 vector (sort + pick) — integer
    arithmetic end to end, so the straggler test below is
    bit-deterministic (no float median)."""
    n = x.shape[0]
    return jnp.sort(x)[(n - 1) // 2]


def fleet_summary(
    tel: Telemetry,
    wait_hist=None,
    shed=None,
    k_mad: int = 4,
    expected_rate_x1000: int = 0,
):
    """The in-graph fleet reduction: one ``[F, NUM_SUMMARY_COLS]``
    int32 summary vector per instance from the fleet telemetry (plus
    the workload gauges), computed ON DEVICE so the host pulls O(F)
    scalars per drain instead of F full rings.

    Per instance: commits + rotations over the retained ring window
    (a true XLA segmented reduction over the ``[F, K]`` ring block —
    the BASELINE aggregation shape), the commit-rate x1000 over that
    window, and nearest-rank p50/p99 of the cumulative commit-latency
    and queue-wait histograms.

    Straggler flagging (in-graph, directional): an instance is flagged
    when its windowed commit rate falls BELOW the fleet median by more
    than ``k_mad * MAD`` plus a noise floor (an eighth of the median,
    min 25 x1000-units), or its latency/wait p99 rises ABOVE the
    fleet median p99 by more than ``k_mad * MAD + 2`` bins — median/
    MAD, not mean/stddev, so one hostile instance cannot drag the
    baseline toward itself. ``expected_rate_x1000 > 0`` adds the
    analytical anchor (the SCALE-Sim-style expected commit rate from
    config, arxiv 2603.22535): an instance below HALF the anchor is
    flagged even if the whole fleet sank together (a fleet-wide
    brownout has no outlier for MAD to see).

    ``wait_hist``/``shed`` are the fleet workload gauges ([F, WB] /
    [F]; zero-sized or None when the workload engine is off). Pure
    jnp — jit it (the fleet serve snapshot does) or call it inside a
    larger program."""
    assert is_fleet(tel), "fleet_summary needs a leading instance axis"
    F = fleet_size_of(tel)
    K = window_of_fleet(tel)
    assert K > 0, "fleet_summary needs a sized telemetry ring"
    ticks = tel.ticks.astype(jnp.int32)  # [F]
    n_win = jnp.minimum(ticks, K)  # [F] valid ring rows
    # Ring-row validity: before the first wrap, slots [0, ticks) hold
    # data; afterwards every slot does.
    slot_valid = (
        jnp.arange(K, dtype=jnp.int32)[None, :] < n_win[:, None]
    )  # [F, K]
    seg_ids = jnp.broadcast_to(
        jnp.arange(F, dtype=jnp.int32)[:, None], (F, K)
    ).ravel()

    def window_sum(col: str):
        vals = jnp.where(
            slot_valid, tel.counters[:, :, COL[col]], 0
        ).ravel()
        return jax.ops.segment_sum(vals, seg_ids, num_segments=F)

    commits = window_sum("commits")
    rotations = window_sum("rotations")
    denom = jnp.maximum(n_win, 1)
    rate = commits * 1000 // denom  # commit_rate_x1000

    p50_lat = _hist_percentile_rows(tel.lat_hist, 50, 100)
    p99_lat = _hist_percentile_rows(tel.lat_hist, 99, 100)
    if wait_hist is not None and wait_hist.ndim == 2 and (
        wait_hist.shape[1] > 0
    ):
        p50_wait = _hist_percentile_rows(wait_hist, 50, 100)
        p99_wait = _hist_percentile_rows(wait_hist, 99, 100)
    else:
        p50_wait = jnp.full((F,), -1, jnp.int32)
        p99_wait = jnp.full((F,), -1, jnp.int32)
    if shed is not None and shed.ndim == 1 and shed.shape[0] == F:
        shed_col = shed.astype(jnp.int32)
    else:
        shed_col = jnp.zeros((F,), jnp.int32)

    # -- straggler test: median/MAD deviation, directional.
    med_r = _int_median(rate)
    mad_r = _int_median(jnp.abs(rate - med_r))
    floor_r = jnp.maximum(med_r // 8, 25)
    low_rate = (med_r - rate) > (k_mad * mad_r + floor_r)

    def high_tail(p):
        med = _int_median(p)
        mad = _int_median(jnp.abs(p - med))
        return (p - med) > (k_mad * mad + 2)

    straggler = low_rate | high_tail(p99_lat) | high_tail(p99_wait)
    if expected_rate_x1000 > 0:
        straggler = straggler | (rate < expected_rate_x1000 // 2)

    return jnp.stack(
        [
            ticks,
            n_win,
            commits,
            rate,
            rotations,
            p50_lat,
            p99_lat,
            p50_wait,
            p99_wait,
            shed_col,
            straggler.astype(jnp.int32),
        ],
        axis=1,
    )


def window_of_fleet(tel: Telemetry) -> int:
    """The ring size K of a fleet telemetry (axis 1 — axis 0 is the
    instance axis)."""
    assert is_fleet(tel)
    return tel.counters.shape[1]


def summary_row_dict(row) -> dict:
    """One instance's summary vector as a name -> int dict (the host
    report / scrape-CSV shape)."""
    import numpy as np

    row = np.asarray(row)
    return {
        name: int(row[i]) for i, name in enumerate(FLEET_SUMMARY_COLS)
    }


# ---------------------------------------------------------------------------
# Host side: one coalesced transfer, then pure-numpy views.
# ---------------------------------------------------------------------------


def fetch(tel: Telemetry) -> Telemetry:
    """One coalesced ``jax.device_get`` of the whole telemetry pytree —
    the epoch-boundary pull (never call inside a tick; the lint
    enforces that)."""
    return jax.device_get(tel)


def series(tel: Telemetry) -> Dict[str, "jnp.ndarray"]:
    """Unroll the ring into chronological per-tick series.

    Returns ``{"tick": [n], "<counter>": [n], ...}`` covering the last
    ``min(ticks, K)`` ticks in time order (oldest first). Works on a
    fetched (host) or device-resident Telemetry."""
    import numpy as np

    tel = jax.device_get(tel)
    K = tel.counters.shape[0]
    total = int(tel.ticks)
    n = min(total, K)
    if n == 0:
        return {name: np.zeros((0,), np.int32) for name in
                ("tick",) + COUNTER_FIELDS}
    # Oldest retained tick sits at slot ticks % K once the ring wrapped.
    order = (int(tel.ticks) - n + np.arange(n)) % K
    out = {"tick": np.arange(total - n, total, dtype=np.int64)}
    rows = np.asarray(tel.counters)[order]
    for name, col in COL.items():
        out[name] = rows[:, col]
    return out


def completed_spans(tel: Telemetry, cursor: int = 0):
    """Completed spans with sequence number >= ``cursor``, as a list of
    dicts (``{"group", "slot_id", "seq", <stage>: tick | -1}``), plus
    the count of spans that aged out of the completion ring before this
    drain (lost) and the new cursor. Works on a fetched or
    device-resident Telemetry."""
    import numpy as np

    tel = jax.device_get(tel)
    SR = tel.span_ring.shape[0]
    total = int(tel.spans_done)
    n = total - int(cursor)
    if n <= 0 or SR == 0:
        return [], max(0, n if SR == 0 else 0), total
    dropped = max(0, n - SR)
    keep = n - dropped
    order = (total - keep + np.arange(keep)) % SR
    rows = np.asarray(tel.span_ring)[order]
    out = []
    for i, row in enumerate(rows):
        d = {"seq": total - keep + i}
        for col, name in enumerate(SPAN_COLS):
            d[name] = int(row[col])
        out.append(d)
    return out, dropped, total


class DrainCursor:
    """Host-side cursor for EXACT partial drains of a telemetry ring:
    each :meth:`drain` call returns precisely the per-tick rows (and
    completed spans) recorded since the previous call — no sample lost
    or double-counted as long as drains happen at least once per ring
    period (``window`` ticks for counters, ``spans * SPAN_RING_FACTOR``
    completions for spans; slower drains report the overrun in
    ``dropped_*`` instead of silently double-counting).

    The serve loop (``harness/serve.py``) drains the PREVIOUS chunk's
    telemetry snapshot through one of these while the next chunk
    computes — the cursor is what makes chunked drains sum to exactly
    the one-shot capture (pinned bit-identical by
    ``tests/test_serve.py``).

    FLEET telemetry (a leading instance axis, :func:`is_fleet`) drains
    through the SAME cursor: the first fleet drain grows one
    sub-cursor per instance and every drain slices the fetched pytree
    per instance through the unchanged single-instance path — chunked
    fleet drains are therefore bit-identical to sequential
    per-instance drains BY CONSTRUCTION (and pinned so by
    ``tests/test_fleet.py``). The fleet result is
    ``{"fleet": F, "instances": [per-instance drain dicts],
    "ticks_total", "dropped_ticks", "dropped_spans"}`` with the
    aggregates summed over instances."""

    def __init__(self, tick: int = 0, span: int = 0):
        self.tick = int(tick)
        self.span = int(span)
        self._fleet: Optional[List["DrainCursor"]] = None

    def _drain_fleet(self, tel: Telemetry) -> dict:
        """One coalesced pull already happened (``tel`` is fetched);
        slice per instance and drain each through its own sub-cursor."""
        F = fleet_size_of(tel)
        if self._fleet is None:
            self._fleet = [
                DrainCursor(self.tick, self.span) for _ in range(F)
            ]
        assert len(self._fleet) == F, (
            f"fleet width changed mid-cursor: {len(self._fleet)} -> {F}"
        )
        insts = [
            self._fleet[i].drain(instance_view(tel, i))
            for i in range(F)
        ]
        return {
            "fleet": F,
            "instances": insts,
            "ticks_total": max(d["ticks_total"] for d in insts),
            "dropped_ticks": sum(d["dropped_ticks"] for d in insts),
            "dropped_spans": sum(d["dropped_spans"] for d in insts),
        }

    def drain(self, tel: Telemetry) -> dict:
        """Drain everything recorded since the last call. ``tel`` may
        be device-resident (one coalesced pull happens here) or already
        fetched (e.g. a serve-loop snapshot). Returns per-tick series
        for the new ticks, the new completed spans, the cumulative
        totals at this drain point, and drop counts for ring overruns.
        Fleet telemetry returns the per-instance form (class
        docstring)."""
        import numpy as np

        tel = jax.device_get(tel)
        if is_fleet(tel):
            return self._drain_fleet(tel)
        K = tel.counters.shape[0]
        total = int(tel.ticks)
        n = total - self.tick
        dropped = max(0, n - K) if K else max(0, n)
        keep = max(0, n - dropped) if K else 0
        out: Dict[str, object] = {
            "ticks_total": total,
            "tick_from": total - keep,
            "dropped_ticks": dropped,
            "totals": {
                name: _unsigned_total(tel.totals[i])
                for i, name in enumerate(COUNTER_FIELDS)
            },
            "lat_hist": np.asarray(tel.lat_hist).copy(),
            "queue_hist": np.asarray(tel.queue_hist).copy(),
        }
        if keep:
            order = (total - keep + np.arange(keep)) % K
            rows = np.asarray(tel.counters)[order]
            out["tick"] = np.arange(total - keep, total, dtype=np.int64)
            for name, col in COL.items():
                out[name] = rows[:, col]
        else:
            out["tick"] = np.zeros((0,), np.int64)
            for name in COUNTER_FIELDS:
                out[name] = np.zeros((0,), np.int32)
        self.tick = total
        spans, span_dropped, self.span = completed_spans(tel, self.span)
        out["spans"] = spans
        out["dropped_spans"] = span_dropped
        return out


def _unsigned_total(value) -> int:
    """Host view of an int32 cumulative counter: reinterpret as
    unsigned so a wrapped counter reads as a reset, never negative."""
    return int(value) & 0xFFFFFFFF


def summary(tel: Telemetry) -> dict:
    """Scalar roll-up: cumulative totals plus windowed per-tick rates
    over the retained ring."""
    import numpy as np

    tel = jax.device_get(tel)
    s = series(tel)
    n = len(s["tick"])
    out = {"ticks": int(tel.ticks), "window": int(tel.counters.shape[0])}
    for i, name in enumerate(COUNTER_FIELDS):
        out[f"{name}_total"] = _unsigned_total(tel.totals[i])
        out[f"{name}_per_tick_windowed"] = (
            float(np.mean(s[name])) if n else 0.0
        )
    return out


def to_dict(tel: Telemetry) -> dict:
    """JSON-serializable capture of the whole telemetry state — the
    interchange format between a run (``bench.py --telemetry``,
    ``TpuSimTransport.telemetry()``) and the dashboard."""
    tel = jax.device_get(tel)
    s = series(tel)
    return {
        "ticks": int(tel.ticks),
        "window": int(tel.counters.shape[0]),
        "series": {k: [int(v) for v in vals] for k, vals in s.items()},
        "totals": {
            name: _unsigned_total(tel.totals[i])
            for i, name in enumerate(COUNTER_FIELDS)
        },
        "lat_hist": [int(v) for v in tel.lat_hist],
        "queue_hist": [int(v) for v in tel.queue_hist],
    }


def exposition_lines(
    tel: Telemetry, labels: Optional[Dict[str, str]] = None
) -> List[str]:
    """Render the telemetry as Prometheus text exposition under the
    unified ``fpx_device_*`` naming scheme (parseable by
    ``monitoring.scrape.parse_exposition``): cumulative ``_total``
    counters, and cumulative-bucket histograms for commit latency
    (ticks) and queue occupancy (fraction of capacity)."""
    tel = jax.device_get(tel)
    label_str = ""
    if labels:
        pairs = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        label_str = "{" + pairs + "}"

    def labeled(extra: Dict[str, str]) -> str:
        merged = dict(labels or {})
        merged.update(extra)
        pairs = ",".join(f'{k}="{v}"' for k, v in sorted(merged.items()))
        return "{" + pairs + "}"

    lines = [
        "# TYPE fpx_device_ticks_total counter",
        f"fpx_device_ticks_total{label_str} {int(tel.ticks)}",
    ]
    for i, name in enumerate(COUNTER_FIELDS):
        metric = f"fpx_device_{name}_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric}{label_str} {_unsigned_total(tel.totals[i])}")
    lines.append("# TYPE fpx_device_commit_latency_ticks histogram")
    cum = 0
    for b, count in enumerate(tel.lat_hist):
        cum += int(count)
        lines.append(
            "fpx_device_commit_latency_ticks_bucket"
            f"{labeled({'le': str(b)})} {cum}"
        )
    lines.append(
        "fpx_device_commit_latency_ticks_bucket"
        f"{labeled({'le': '+Inf'})} {cum}"
    )
    lines.append(f"fpx_device_commit_latency_ticks_count{label_str} {cum}")
    lines.append("# TYPE fpx_device_queue_occupancy histogram")
    cum = 0
    for b, count in enumerate(tel.queue_hist):
        cum += int(count)
        le = f"{(b + 1) / QUEUE_BINS:.4f}"
        lines.append(
            f"fpx_device_queue_occupancy_bucket{labeled({'le': le})} {cum}"
        )
    lines.append(
        f"fpx_device_queue_occupancy_bucket{labeled({'le': '+Inf'})} {cum}"
    )
    lines.append(f"fpx_device_queue_occupancy_count{label_str} {cum}")
    return lines
