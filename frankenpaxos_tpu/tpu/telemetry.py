"""Device-side telemetry: per-tick metric rings that live INSIDE the
compiled ``lax.scan``.

The only visibility into a compiled tick loop used to be host-side
``stats()`` pulls between ``run()`` segments — the loop itself was a
black box. Compartmentalized MultiPaxos (PAPERS: arxiv 2012.15762) makes
the case that *finding the bottleneck component is the optimization
method* for SMR; that needs per-phase counters with per-tick resolution,
not end-of-segment totals. This module is the repo-wide contract for
that: one metrics struct, one ring-buffer idiom, one exposition format.

Design:

  * :class:`Telemetry` is a pytree carried in every batched backend's
    ``*State`` dataclass, so it threads through ``run_ticks``'s scan
    carry (and through donation, sharding, vmap, and ``widen_state``)
    with no signature changes anywhere.
  * Each ``tick`` calls :func:`record` with per-tick event counts that
    the tick has ALREADY computed for its own bookkeeping (quorum sums,
    retire counts, cumulative-counter deltas) — int32 adds on values
    resident in registers, plus ONE dynamic-update-slice of a
    ``[NUM_COLS]`` row into the ``[K, NUM_COLS]`` ring per tick. All
    leaves are int32 (the dtype policy's accumulator width), so
    ``widen_state`` is a no-op and narrowed/widened runs stay
    bit-identical.
  * The ring keeps the last ``K`` ticks (slot ``= ticks % K``), so a
    single coalesced ``jax.device_get`` at an epoch boundary yields a
    full per-tick time series with zero host sync inside the hot loop
    (the pull itself still waits for in-flight device work, like any
    transfer — the point is the LOOP never syncs).
    Ring contents are invariant to K where windows overlap: the value
    recorded for tick t is the same regardless of window size.
  * ``window = 0`` disables telemetry STRUCTURALLY: :func:`record`
    no-ops at trace time (K is a static shape), so XLA dead-code
    eliminates every count that feeds only telemetry — the zero-overhead
    baseline the ``bench.py --telemetry`` budget check compares against.

Exposition naming scheme (host + device metrics unify under it):
``fpx_device_*`` for in-graph metrics (this module), ``fpx_host_*`` for
transport-level trace spans; counters end in ``_total``, histograms use
Prometheus cumulative ``_bucket{le=...}`` lines. Rendered by
:func:`exposition_lines` and consumed by ``monitoring/scrape.py`` /
``monitoring/dashboard.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import LAT_BINS

# Per-tick ring columns. The first eight are event counters (events that
# happened THIS tick); queue_depth is a gauge sampled at tick end
# (in-flight work items — ring occupancy / window backlog, per backend).
COUNTER_FIELDS = (
    "proposals",
    "phase1_msgs",
    "phase2_msgs",
    "commits",
    "executes",
    "drops",
    "retries",
    "leader_changes",
    "queue_depth",
)
NUM_COLS = len(COUNTER_FIELDS)
COL = {name: i for i, name in enumerate(COUNTER_FIELDS)}

TELEM_WINDOW = 128  # default ring size K (ticks)
QUEUE_BINS = 32  # queue-depth histogram bins (occupancy fractions)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Telemetry:
    """Device-resident metric ring. All leaves int32 (accumulator width
    under the dtype policy — never narrowed, never widened; x64 is off
    in this runtime). ``totals`` therefore wraps mod 2^32 on very long
    runs — the busiest flagship column (phase2_msgs, ~50k/tick) wraps
    after ~80k ticks, ~10x a full bench.py run. Host-side views
    (:func:`summary`, :func:`exposition_lines`) reinterpret the totals
    as unsigned so a wrapped counter reads as a Prometheus counter
    reset (which ``rate()`` handles), never as a negative sample."""

    ticks: jnp.ndarray  # [] ticks recorded since creation
    counters: jnp.ndarray  # [K, NUM_COLS] per-tick ring (slot = t % K)
    totals: jnp.ndarray  # [NUM_COLS] cumulative sums of every column
    lat_hist: jnp.ndarray  # [LAT_BINS] commit-latency histogram (ticks)
    queue_hist: jnp.ndarray  # [QUEUE_BINS] occupancy-fraction histogram


def make_telemetry(window: int = TELEM_WINDOW) -> Telemetry:
    """A zeroed telemetry ring of ``window`` ticks; ``window=0`` turns
    the subsystem off structurally (record() becomes a trace-time
    no-op and XLA removes the feeding computations)."""
    assert window >= 0
    return Telemetry(
        ticks=jnp.zeros((), jnp.int32),
        counters=jnp.zeros((window, NUM_COLS), jnp.int32),
        totals=jnp.zeros((NUM_COLS,), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        queue_hist=jnp.zeros((QUEUE_BINS,), jnp.int32),
    )


def window(tel: Telemetry) -> int:
    """The ring size K — a static shape, readable at trace time."""
    return tel.counters.shape[0]


def record(
    tel: Telemetry,
    *,
    proposals=0,
    phase1_msgs=0,
    phase2_msgs=0,
    commits=0,
    executes=0,
    drops=0,
    retries=0,
    leader_changes=0,
    queue_depth=0,
    queue_capacity: int = 0,
    lat_hist_delta: Optional[jnp.ndarray] = None,
) -> Telemetry:
    """Record one tick. Counter args are this tick's event counts
    (scalars, traced or Python ints); ``queue_depth`` is the end-of-tick
    backlog gauge, binned into ``queue_hist`` as a fraction of the
    static ``queue_capacity`` (0 = don't bin). ``lat_hist_delta`` is
    this tick's [LAT_BINS] commit-latency increment (most backends
    already compute it as a ``segment_sum``; pass the same array).

    With a zero-width ring this is a trace-time no-op except the tick
    count — the disabled-telemetry baseline costs nothing."""
    ticks = tel.ticks + 1
    if window(tel) == 0:
        return dataclasses.replace(tel, ticks=ticks)
    row = jnp.stack(
        [
            jnp.asarray(v, jnp.int32).reshape(())
            for v in (
                proposals,
                phase1_msgs,
                phase2_msgs,
                commits,
                executes,
                drops,
                retries,
                leader_changes,
                queue_depth,
            )
        ]
    )
    slot = jnp.mod(tel.ticks, window(tel))
    counters = jax.lax.dynamic_update_slice(
        tel.counters, row[None, :], (slot, jnp.int32(0))
    )
    lat_hist = tel.lat_hist
    if lat_hist_delta is not None:
        lat_hist = lat_hist + lat_hist_delta.astype(jnp.int32)
    queue_hist = tel.queue_hist
    if queue_capacity > 0:
        qbin = jnp.clip(
            jnp.asarray(queue_depth, jnp.int32) * QUEUE_BINS
            // jnp.int32(queue_capacity),
            0,
            QUEUE_BINS - 1,
        )
        queue_hist = queue_hist.at[qbin].add(1)
    return Telemetry(
        ticks=ticks,
        counters=counters,
        totals=tel.totals + row,
        lat_hist=lat_hist,
        queue_hist=queue_hist,
    )


# ---------------------------------------------------------------------------
# Host side: one coalesced transfer, then pure-numpy views.
# ---------------------------------------------------------------------------


def fetch(tel: Telemetry) -> Telemetry:
    """One coalesced ``jax.device_get`` of the whole telemetry pytree —
    the epoch-boundary pull (never call inside a tick; the lint
    enforces that)."""
    return jax.device_get(tel)


def series(tel: Telemetry) -> Dict[str, "jnp.ndarray"]:
    """Unroll the ring into chronological per-tick series.

    Returns ``{"tick": [n], "<counter>": [n], ...}`` covering the last
    ``min(ticks, K)`` ticks in time order (oldest first). Works on a
    fetched (host) or device-resident Telemetry."""
    import numpy as np

    tel = jax.device_get(tel)
    K = tel.counters.shape[0]
    total = int(tel.ticks)
    n = min(total, K)
    if n == 0:
        return {name: np.zeros((0,), np.int32) for name in
                ("tick",) + COUNTER_FIELDS}
    # Oldest retained tick sits at slot ticks % K once the ring wrapped.
    order = (int(tel.ticks) - n + np.arange(n)) % K
    out = {"tick": np.arange(total - n, total, dtype=np.int64)}
    rows = np.asarray(tel.counters)[order]
    for name, col in COL.items():
        out[name] = rows[:, col]
    return out


def _unsigned_total(value) -> int:
    """Host view of an int32 cumulative counter: reinterpret as
    unsigned so a wrapped counter reads as a reset, never negative."""
    return int(value) & 0xFFFFFFFF


def summary(tel: Telemetry) -> dict:
    """Scalar roll-up: cumulative totals plus windowed per-tick rates
    over the retained ring."""
    import numpy as np

    tel = jax.device_get(tel)
    s = series(tel)
    n = len(s["tick"])
    out = {"ticks": int(tel.ticks), "window": int(tel.counters.shape[0])}
    for i, name in enumerate(COUNTER_FIELDS):
        out[f"{name}_total"] = _unsigned_total(tel.totals[i])
        out[f"{name}_per_tick_windowed"] = (
            float(np.mean(s[name])) if n else 0.0
        )
    return out


def to_dict(tel: Telemetry) -> dict:
    """JSON-serializable capture of the whole telemetry state — the
    interchange format between a run (``bench.py --telemetry``,
    ``TpuSimTransport.telemetry()``) and the dashboard."""
    tel = jax.device_get(tel)
    s = series(tel)
    return {
        "ticks": int(tel.ticks),
        "window": int(tel.counters.shape[0]),
        "series": {k: [int(v) for v in vals] for k, vals in s.items()},
        "totals": {
            name: _unsigned_total(tel.totals[i])
            for i, name in enumerate(COUNTER_FIELDS)
        },
        "lat_hist": [int(v) for v in tel.lat_hist],
        "queue_hist": [int(v) for v in tel.queue_hist],
    }


def exposition_lines(
    tel: Telemetry, labels: Optional[Dict[str, str]] = None
) -> List[str]:
    """Render the telemetry as Prometheus text exposition under the
    unified ``fpx_device_*`` naming scheme (parseable by
    ``monitoring.scrape.parse_exposition``): cumulative ``_total``
    counters, and cumulative-bucket histograms for commit latency
    (ticks) and queue occupancy (fraction of capacity)."""
    tel = jax.device_get(tel)
    label_str = ""
    if labels:
        pairs = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        label_str = "{" + pairs + "}"

    def labeled(extra: Dict[str, str]) -> str:
        merged = dict(labels or {})
        merged.update(extra)
        pairs = ",".join(f'{k}="{v}"' for k, v in sorted(merged.items()))
        return "{" + pairs + "}"

    lines = [
        "# TYPE fpx_device_ticks_total counter",
        f"fpx_device_ticks_total{label_str} {int(tel.ticks)}",
    ]
    for i, name in enumerate(COUNTER_FIELDS):
        metric = f"fpx_device_{name}_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric}{label_str} {_unsigned_total(tel.totals[i])}")
    lines.append("# TYPE fpx_device_commit_latency_ticks histogram")
    cum = 0
    for b, count in enumerate(tel.lat_hist):
        cum += int(count)
        lines.append(
            "fpx_device_commit_latency_ticks_bucket"
            f"{labeled({'le': str(b)})} {cum}"
        )
    lines.append(
        "fpx_device_commit_latency_ticks_bucket"
        f"{labeled({'le': '+Inf'})} {cum}"
    )
    lines.append(f"fpx_device_commit_latency_ticks_count{label_str} {cum}")
    lines.append("# TYPE fpx_device_queue_occupancy histogram")
    cum = 0
    for b, count in enumerate(tel.queue_hist):
        cum += int(count)
        le = f"{(b + 1) / QUEUE_BINS:.4f}"
        lines.append(
            f"fpx_device_queue_occupancy_bucket{labeled({'le': le})} {cum}"
        )
    lines.append(
        f"fpx_device_queue_occupancy_bucket{labeled({'le': '+Inf'})} {cum}"
    )
    lines.append(f"fpx_device_queue_occupancy_count{label_str} {cum}")
    return lines
