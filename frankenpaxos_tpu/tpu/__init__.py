"""The TPU-native simulation backend.

This package is the point of the project (BASELINE.json north star): a
fourth-style transport backend where per-actor protocol state is flattened
into batched JAX arrays, ``Actor.receive`` handlers become vectorized step
functions over a replica axis, quorum/ballot aggregation compiles to XLA
reductions, and whole-cluster simulation ticks run under ``jax.jit`` +
``lax.scan``, sharded over a ``jax.sharding.Mesh`` for multi-chip scale.
"""

from frankenpaxos_tpu.tpu import (
    caspaxos_batched,
    compartmentalized_batched,
    craq_batched,
    epaxos_batched,
    fasterpaxos_batched,
    fastmultipaxos_batched,
    fastpaxos_batched,
    faults,
    grid_batched,
    horizontal_batched,
    mencius_batched,
    scalog_batched,
    unreplicated_batched,
    vanillamencius_batched,
)
from frankenpaxos_tpu.tpu.compartmentalized_batched import (
    BatchedCompartmentalizedConfig,
    BatchedCompartmentalizedState,
)
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan
from frankenpaxos_tpu.tpu.caspaxos_batched import (
    BatchedCasPaxosConfig,
    BatchedCasPaxosState,
)
from frankenpaxos_tpu.tpu.fastpaxos_batched import (
    BatchedFastPaxosConfig,
    BatchedFastPaxosState,
)
from frankenpaxos_tpu.tpu.craq_batched import (
    BatchedCraqConfig,
    BatchedCraqState,
)
from frankenpaxos_tpu.tpu.epaxos_batched import (
    BatchedEPaxosConfig,
    BatchedEPaxosState,
)
from frankenpaxos_tpu.tpu.mencius_batched import (
    BatchedMenciusConfig,
    BatchedMenciusState,
)
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    BatchedMultiPaxosConfig,
    BatchedMultiPaxosState,
    check_invariants,
    init_state,
    leader_change,
    reconfigure,
    run_ticks,
    tick,
)
from frankenpaxos_tpu.tpu.transport import TpuSimTransport

__all__ = [
    "BatchedCasPaxosConfig",
    "BatchedCasPaxosState",
    "BatchedCompartmentalizedConfig",
    "BatchedCompartmentalizedState",
    "caspaxos_batched",
    "compartmentalized_batched",
    "BatchedCraqConfig",
    "BatchedCraqState",
    "craq_batched",
    "BatchedEPaxosConfig",
    "BatchedEPaxosState",
    "BatchedFastPaxosConfig",
    "BatchedFastPaxosState",
    "fasterpaxos_batched",
    "fastmultipaxos_batched",
    "fastpaxos_batched",
    "BatchedMenciusConfig",
    "BatchedMenciusState",
    "BatchedMultiPaxosConfig",
    "BatchedMultiPaxosState",
    "FaultPlan",
    "LifecyclePlan",
    "WorkloadPlan",
    "TpuSimTransport",
    "check_invariants",
    "epaxos_batched",
    "faults",
    "grid_batched",
    "init_state",
    "leader_change",
    "horizontal_batched",
    "mencius_batched",
    "reconfigure",
    "scalog_batched",
    "unreplicated_batched",
    "vanillamencius_batched",
    "run_ticks",
    "tick",
]
