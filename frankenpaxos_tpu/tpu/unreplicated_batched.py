"""Batched unreplicated state machine — the throughput CEILING baseline.

The reference's headline figure (eurosys fig1) frames compartmentalized
MultiPaxos against an UNREPLICATED state machine: one server, no
consensus, just client -> server -> client round trips — the ceiling any
replication protocol is measured against (878k vs 983k cmd/s there,
89%). This is that baseline for the batched world: ``G`` independent
servers, a ring of ``W`` in-flight ops each, an op is one request hop +
execute-on-arrival + one reply hop (``unreplicated/Server.scala``;
per-actor analog ``protocols/unreplicated.py``). Everything else (PRNG
latencies, ring accounting, stats) matches the consensus backends, so
``ceiling_fraction = multipaxos committed/s / unreplicated ops/s`` is an
apples-to-apples number.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_latency,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState

U_EMPTY = 0
U_REQ = 1  # request in flight to the server
U_REP = 2  # reply in flight to the client


@dataclasses.dataclass(frozen=True)
class BatchedUnreplicatedConfig:
    num_servers: int = 4  # G
    window: int = 32  # W in-flight ops per server
    ops_per_tick: int = 4  # K new ops per server per tick
    lat_min: int = 1
    lat_max: int = 3
    # Unified in-graph fault injection (tpu/faults.py), TCP semantics:
    # drops become retransmission penalties on the request/reply hops;
    # a SERVER-axis partition (side bits over the G servers) buffers
    # ops to cut servers until the heal tick. The ceiling baseline
    # degrades under faults exactly like the consensus backends'
    # message planes, keeping ceiling_fraction apples-to-apples.
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes the per-server
    # admission of new ops (arrival process x Zipf skew, FIFO backlog,
    # closed-loop client window). WorkloadPlan.none() is a structural
    # no-op (saturation).
    workload: WorkloadPlan = WorkloadPlan.none()

    def __post_init__(self):
        assert self.window >= 2 * self.ops_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        self.faults.validate(axis=self.num_servers)
        self.workload.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedUnreplicatedState:
    status: jnp.ndarray  # [G, W]
    issue: jnp.ndarray  # [G, W]
    arrival: jnp.ndarray  # [G, W] next event tick
    executed: jnp.ndarray  # [G] per-server executed ops
    done: jnp.ndarray  # [] completed round trips
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedUnreplicatedConfig) -> BatchedUnreplicatedState:
    G, W = cfg.num_servers, cfg.window
    return BatchedUnreplicatedState(
        status=jnp.zeros((G, W), DTYPE_STATUS),
        issue=jnp.full((G, W), INF, jnp.int32),
        arrival=jnp.full((G, W), INF, jnp.int32),
        executed=jnp.zeros((G,), jnp.int32),
        done=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(cfg.workload, G, cfg.faults),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedUnreplicatedConfig,
    state: BatchedUnreplicatedState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedUnreplicatedState:
    G, W = cfg.num_servers, cfg.window
    bits = jax.random.bits(key, (G, W))  # [0:8) req lat, [8:16) rep lat
    req_lat = bit_latency(bits, 0, cfg.lat_min, cfg.lat_max)
    rep_lat = bit_latency(bits, 8, cfg.lat_min, cfg.lat_max)

    # Unified fault injection (tpu/faults.py), TCP semantics: drop
    # penalties + jitter on both hops; a cut server's ops buffer until
    # the heal tick. none() skips everything at trace time.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    req_arr = t + req_lat
    rep_arr = t + rep_lat
    if fp.active:
        kf = faults_mod.fault_key(key)
        req_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 0), (G, W), req_lat, rates=frates
        )
        rep_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 1), (G, W), rep_lat, rates=frates
        )
        req_arr = t + req_lat
        rep_arr = t + rep_lat
        if fp.has_partition:
            cut = ~faults_mod.partition_row(fp, t, G)[:, None]
            req_arr = faults_mod.defer_to_heal(fp, req_arr, cut)
            rep_arr = faults_mod.defer_to_heal(fp, rep_arr, cut)

    # Server executes on arrival and replies (Server.scala handleRequest).
    at_server = (state.status == U_REQ) & (state.arrival == t)
    executed = state.executed + jnp.sum(at_server, axis=1)
    status = jnp.where(at_server, U_REP, state.status)
    arrival = jnp.where(at_server, rep_arr, state.arrival)

    # Client receives the reply.
    done_now = (status == U_REP) & (arrival <= t)
    lat = jnp.where(done_now, t - state.issue, 0)
    done = state.done + jnp.sum(done_now)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        done_now.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    status = jnp.where(done_now, U_EMPTY, status)
    arrival = jnp.where(done_now, INF, arrival)
    issue = jnp.where(done_now, INF, state.issue)

    # New ops. Under a workload plan the static ops_per_tick knob is
    # replaced by the per-server admission cap; the client observes a
    # completion at the reply (done_now).
    empty = status == U_EMPTY
    rank = jnp.cumsum(empty.astype(jnp.int32), axis=1)
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, G)
        adm = workload_mod.admission(wl, wls, wl_writes)
        new = empty & (rank <= adm[:, None])
    else:
        new = empty & (rank <= cfg.ops_per_tick)
    status = jnp.where(new, U_REQ, status)
    issue = jnp.where(new, t, issue)
    arrival = jnp.where(new, req_arr, arrival)
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes,
            jnp.sum(new, axis=1), jnp.sum(done_now, axis=1),
        )

    # Telemetry: request hops are this backend's "phase 2" plane
    # (client -> server -> client; no consensus phases exist).
    tel = record(
        state.telemetry,
        proposals=jnp.sum(new),
        phase2_msgs=jnp.sum(new) + jnp.sum(at_server),
        commits=done - state.done,
        executes=jnp.sum(at_server),
        queue_depth=jnp.sum(status != U_EMPTY),
        queue_capacity=G * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )
    return BatchedUnreplicatedState(
        status=status,
        issue=issue,
        arrival=arrival,
        executed=executed,
        done=done,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedUnreplicatedConfig,
    state: BatchedUnreplicatedState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedUnreplicatedState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedUnreplicatedConfig, state: BatchedUnreplicatedState, t
) -> dict:
    return {
        "status_ok": jnp.all(
            (state.status >= U_EMPTY) & (state.status <= U_REP)
        ),
        # Executed counts every request arrival; done lags by in-flight
        # replies.
        "books_ok": state.done <= jnp.sum(state.executed),
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
    }


def stats(cfg, state, t) -> dict:
    done = int(state.done)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (done + 1) // 2)).argmax())
        if done
        else -1
    )
    return {
        "ticks": int(t),
        "done": done,
        "latency_p50_ticks": p50,
        "latency_mean_ticks": float(state.lat_sum) / done if done else -1.0,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedUnreplicatedConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedUnreplicatedConfig(
        num_servers=4, window=16, ops_per_tick=2, faults=faults,
        workload=workload,
    )
