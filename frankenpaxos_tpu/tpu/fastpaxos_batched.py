"""Batched Fast Paxos as a single XLA program.

Fast Paxos (reference ``fastpaxos/``; per-actor analog
``protocols/fastpaxos.py``): clients propose straight to the acceptors in
fast round 0 and count Phase2bs themselves; a FAST quorum of
``f + ⌊(f+1)/2⌋ + 1`` identical round-0 votes (of ``n = 2f+1``) chooses
without a leader. Colliding proposals fall back to a classic round: the
leader runs phase 1, and for round-0 votes the O4 rule applies — a value
voted by a MAJORITY OF A QUORUM (``⌊(f+1)/2⌋ + 1``) must be picked
(``fastpaxos/Leader.scala``; ``Util.popularItems``), else any value is
safe (we use proposer 0's, the leader-default of the per-actor impl).

TPU-first design: ``G x W`` independent single-decree instances are the
replica axis (each group's ring retires chosen instances and admits new
ones — consensus instances, not log slots, because Fast Paxos here is
single-decree). Per instance TWO candidate proposers race; with
``conflict_rate`` both propose (the collision the fast path cannot
absorb). Acceptors vote round-0 for the FIRST arrival; simultaneous
arrivals break toward proposer 0 (a deterministic stand-in for link
order). A recovery timeout moves a stuck instance to the classic path
even while round-0 votes are still in flight — the case that makes the
O4 rule load-bearing: the classic round must re-discover a possibly
fast-chosen value from the phase-1 vote reports alone.

The safety ledger ``fp_committed_value`` records, per instance, any value
that ever held a fast quorum of round-0 votes in the acceptor arrays
(whether or not a counter observed it); ``check_invariants`` asserts the
finally chosen value never disagrees with it.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_ROUND,
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_latency,
)
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Instance status.
I_EMPTY = 0
I_FAST = 1  # round-0 proposals / votes in flight
I_REC1 = 2  # classic phase 1 in flight
I_REC2 = 3  # classic phase 2 in flight
I_CHOSEN = 4

NO_VALUE = -1


@dataclasses.dataclass(frozen=True)
class BatchedFastPaxosConfig:
    """G groups x W in-flight single-decree instances, n = 2f+1 acceptors
    per group."""

    f: int = 1
    num_groups: int = 4
    window: int = 16  # W: in-flight instances per group
    instances_per_tick: int = 2  # K: new instances issued per group
    conflict_rate: float = 0.2  # P(both proposers race on an instance)
    lat_min: int = 1
    lat_max: int = 3
    recovery_timeout: int = 12  # ticks in I_FAST before classic recovery
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + an acceptor-axis partition on the round-0
    # proposal planes (UDP semantics — the recovery timeout rescues
    # stuck instances through the classic round); the classic dn/up
    # exchange is TCP (delay-only + defer-to-heal), so recovery itself
    # cannot deadlock. crash/revive drives the per-group round-0
    # proposer pair (which is also the vote-counting client role):
    # dead proposers issue nothing and observe nothing; replies
    # persist, so a revival resumes the gated transitions and the
    # recovery timeout rescues instances that starved while dead.
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-group
    # instance admission; a completion is a learned decision.
    # WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum(self) -> int:
        return self.f + 1

    @property
    def quorum_majority(self) -> int:
        return (self.f + 1) // 2 + 1

    @property
    def fast_quorum(self) -> int:
        return self.f + self.quorum_majority

    def __post_init__(self):
        assert self.f >= 1
        assert self.window >= 2 * self.instances_per_tick
        assert 0.0 <= self.conflict_rate <= 1.0
        assert 1 <= self.lat_min <= self.lat_max
        assert self.recovery_timeout >= 2 * self.lat_max
        self.faults.validate(axis=self.n)
        self.workload.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedFastPaxosState:
    """Shapes: [G, W] instances, [A, G, W] per-acceptor."""

    status: jnp.ndarray  # [G, W] I_*
    conflicted: jnp.ndarray  # [G, W] both proposers raced
    issue_tick: jnp.ndarray  # [G, W]
    rec_value: jnp.ndarray  # [G, W] value the classic round proposes
    chosen_value: jnp.ndarray  # [G, W] (NO_VALUE until chosen)
    chosen_fast: jnp.ndarray  # [G, W] chosen on the fast path
    retire_at: jnp.ndarray  # [G, W] tick a chosen instance leaves the ring
    next_inst: jnp.ndarray  # [G] per-group instance sequence number
    inst_id: jnp.ndarray  # [G, W] instance sequence number in the slot

    # Acceptors (per instance: single-decree state).
    acc_round: jnp.ndarray  # [A, G, W] 0 = fast round, 1 = classic
    vote_round: jnp.ndarray  # [A, G, W] -1 = none
    vote_value: jnp.ndarray  # [A, G, W]
    p0_arrival: jnp.ndarray  # [A, G, W] proposer-0 round-0 proposal
    p1_arrival: jnp.ndarray  # [A, G, W] proposer-1 round-0 proposal
    dn_arrival: jnp.ndarray  # [A, G, W] classic-phase message to acceptor
    # The phase the classic message was sent FOR (1 = Phase1a, 2 =
    # Phase2a), captured at send time — the message carries its phase,
    # matching the captured-at-send discipline of caspaxos_batched,
    # instead of inferring it from the counter's live status at delivery
    # (which would misread stragglers under resends/multiple rounds).
    dn_phase: jnp.ndarray  # [A, G, W] 0 = none
    up_arrival: jnp.ndarray  # [A, G, W] reply back to the counter

    # Safety ledger: any value that ever held a fast quorum of round-0
    # votes (set once, device-side).
    fp_committed_value: jnp.ndarray  # [G, W]

    # Round-0 proposer liveness (the crash/revive axis of PR 3
    # follow-up (b), matching the fastmultipaxos treatment): the
    # per-group proposer pair + its counter role. Dead proposers issue
    # nothing and observe nothing; arrived replies persist, so a
    # revival resumes every gated transition and the recovery timeout
    # rescues instances that starved while dead.
    prop_alive: jnp.ndarray  # [G]

    # Stats.
    chosen_total: jnp.ndarray  # []
    chosen_fast_total: jnp.ndarray  # []
    conflicts_total: jnp.ndarray  # []
    recoveries: jnp.ndarray  # []
    safety_violations: jnp.ndarray  # [] chosen != fp_committed ledger
    lat_sum: jnp.ndarray  # []
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedFastPaxosConfig) -> BatchedFastPaxosState:
    G, W, A = cfg.num_groups, cfg.window, cfg.n
    return BatchedFastPaxosState(
        status=jnp.zeros((G, W), DTYPE_STATUS),
        conflicted=jnp.zeros((G, W), bool),
        issue_tick=jnp.full((G, W), INF, jnp.int32),
        rec_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        chosen_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        chosen_fast=jnp.zeros((G, W), bool),
        retire_at=jnp.full((G, W), INF, jnp.int32),
        next_inst=jnp.zeros((G,), jnp.int32),
        inst_id=jnp.full((G, W), -1, jnp.int32),
        acc_round=jnp.zeros((A, G, W), DTYPE_ROUND),
        vote_round=jnp.full((A, G, W), -1, DTYPE_ROUND),
        vote_value=jnp.full((A, G, W), NO_VALUE, jnp.int32),
        p0_arrival=jnp.full((A, G, W), INF, jnp.int32),
        p1_arrival=jnp.full((A, G, W), INF, jnp.int32),
        dn_arrival=jnp.full((A, G, W), INF, jnp.int32),
        dn_phase=jnp.zeros((A, G, W), DTYPE_STATUS),
        up_arrival=jnp.full((A, G, W), INF, jnp.int32),
        fp_committed_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        prop_alive=jnp.ones((G,), bool),
        chosen_total=jnp.zeros((), jnp.int32),
        chosen_fast_total=jnp.zeros((), jnp.int32),
        conflicts_total=jnp.zeros((), jnp.int32),
        recoveries=jnp.zeros((), jnp.int32),
        safety_violations=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_groups, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def _values_of(inst_id: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The two candidate values of an instance: 2*id and 2*id+1 (globally
    distinct, parity = proposer)."""
    return inst_id * 2, inst_id * 2 + 1


def tick(
    cfg: BatchedFastPaxosConfig,
    state: BatchedFastPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedFastPaxosState:
    G, W, A = cfg.num_groups, cfg.window, cfg.n
    FQ, CQ, MAJ = cfg.fast_quorum, cfg.classic_quorum, cfg.quorum_majority
    k3, k2 = jax.random.split(key)
    bits3 = jax.random.bits(k3, (A, G, W))  # [0:8) p0 lat, [8:16) p1 lat,
    #                                         [16:24) dn lat, [24:32) up lat
    bits2 = jax.random.bits(k2, (G, W))  # [0:8) conflict, [8:16) retire lat
    p0_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    p1_lat = bit_latency(bits3, 8, cfg.lat_min, cfg.lat_max)
    dn_lat = bit_latency(bits3, 16, cfg.lat_min, cfg.lat_max)
    up_lat = bit_latency(bits3, 24, cfg.lat_min, cfg.lat_max)
    ret_lat = bit_latency(bits2, 8, cfg.lat_min, cfg.lat_max)

    # Unified fault injection (tpu/faults.py): UDP semantics on the
    # round-0 proposal planes, TCP (delay + defer-to-heal) on the
    # classic dn/up exchange. none() skips everything at trace time.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    p0_del = p1_del = None
    dn_arr = t + dn_lat
    up_arr = t + up_lat
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, A)[:, None, None]
        p0_del, p0_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (A, G, W), p0_lat, link_up,
            rates=frates,
        )
        p1_del, p1_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (A, G, W), p1_lat, link_up,
            rates=frates,
        )
        dn_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 2), (A, G, W), dn_lat, rates=frates
        )
        up_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 3), (A, G, W), up_lat, rates=frates
        )
        dn_arr = t + dn_lat
        up_arr = t + up_lat
        if fp.has_partition:
            cut = ~link_up
            dn_arr = faults_mod.defer_to_heal(fp, dn_arr, cut)
            up_arr = faults_mod.defer_to_heal(fp, up_arr, cut)

    # Proposer crash/revive (PR 3 follow-up (b), the fastmultipaxos
    # treatment): the per-group round-0 proposer pair (which is also
    # the vote-counting client role) is the crash axis. Guarded on
    # has_crash so a none/crash-free plan traces the exact pre-crash
    # program.
    prop_alive = state.prop_alive
    revived = None
    if fp.has_crash:
        new_alive = faults_mod.crash_step(
            fp, faults_mod.fault_key(key, 9), prop_alive, rates=frates
        )
        revived = new_alive & ~prop_alive
        prop_alive = new_alive

    status = state.status
    v0, v1 = _values_of(state.inst_id)

    # ---- 1. Acceptors process round-0 proposals (FpAcceptor: vote iff
    # still in round 0 and unvoted; first arrival wins, simultaneous
    # arrivals break toward proposer 0).
    p0_now = state.p0_arrival == t
    p1_now = state.p1_arrival == t
    can_fast = (state.acc_round == 0) & (state.vote_round < 0)
    take0 = p0_now & can_fast
    take1 = p1_now & can_fast & ~take0
    voted = take0 | take1
    vote_round = jnp.where(voted, 0, state.vote_round)
    vote_value = jnp.where(
        take0, v0[None, :, :], jnp.where(take1, v1[None, :, :], state.vote_value)
    )
    up_arrival = jnp.where(voted, up_arr, state.up_arrival)
    # A second proposal arriving later at a voted/promoted acceptor is
    # simply dropped (the acceptor nacks in the reference; the counter
    # here never needs the nack — timeouts cover it).
    p0_arrival = jnp.where(p0_now, INF, state.p0_arrival)
    p1_arrival = jnp.where(p1_now, INF, state.p1_arrival)

    # ---- 2. Classic-phase messages at acceptors (dn_arrival): the phase
    # each message carries was captured at SEND time (dn_phase) — phase
    # 1a promotes to round 1 and reports votes; phase 2a casts a round-1
    # vote.
    dn_now = state.dn_arrival == t
    p1a_now = dn_now & (state.dn_phase == 1)
    p2a_now = dn_now & (state.dn_phase == 2)
    acc_round = jnp.where(p1a_now | p2a_now, 1, state.acc_round)
    vote_round = jnp.where(p2a_now, 1, vote_round)
    vote_value = jnp.where(p2a_now, state.rec_value[None, :, :], vote_value)
    up_arrival = jnp.where(p1a_now | p2a_now, up_arr, up_arrival)
    dn_arrival = jnp.where(dn_now, INF, state.dn_arrival)
    dn_phase = jnp.where(dn_now, 0, state.dn_phase)

    # ---- 3. Safety ledger: a value holding a FAST quorum of round-0
    # votes in the acceptor arrays is committed, observed or not.
    n_v0 = jnp.sum((vote_round == 0) & (vote_value == v0[None, :, :]), axis=0)
    n_v1 = jnp.sum((vote_round == 0) & (vote_value == v1[None, :, :]), axis=0)
    fast_committed = jnp.where(
        n_v0 >= FQ, v0, jnp.where(n_v1 >= FQ, v1, NO_VALUE)
    )
    fp_committed_value = jnp.where(
        (state.fp_committed_value == NO_VALUE) & (fast_committed >= 0),
        fast_committed,
        state.fp_committed_value,
    )

    # ---- 4. Counters observe replies. Replies carry the acceptor's
    # (vote_round, vote_value); an arrived reply is up_arrival <= t.
    arrived = up_arrival <= t

    # (a) Fast path (FpClient.handlePhase2b): FQ identical round-0 votes
    # among arrived replies choose the value.
    a_v0 = jnp.sum(
        arrived & (vote_round == 0) & (vote_value == v0[None, :, :]), axis=0
    )
    a_v1 = jnp.sum(
        arrived & (vote_round == 0) & (vote_value == v1[None, :, :]), axis=0
    )
    fast_ok = (status == I_FAST) & ((a_v0 >= FQ) | (a_v1 >= FQ))
    fast_val = jnp.where(a_v0 >= FQ, v0, v1)

    # (b) Fast-path exhaustion or timeout -> classic recovery
    # (FpLeader.leaderChange / repropose): all n replies arrived with no
    # fast quorum, or the instance sat in I_FAST for recovery_timeout.
    n_arrived = jnp.sum(arrived, axis=0)
    stuck = (status == I_FAST) & ~fast_ok & (
        (n_arrived >= A)
        | (t - state.issue_tick >= cfg.recovery_timeout)
    )

    # A dead proposer/counter observes nothing: no fast choice, no
    # recovery kickoff, no phase completions. Replies persist in
    # up_arrival, so revival resumes every gated transition on the
    # spot, and the recovery timeout (issue_tick is untouched by the
    # crash) rescues instances that starved while the group was dead.
    if fp.has_crash:
        alive_gw = prop_alive[:, None]
        fast_ok = fast_ok & alive_gw
        stuck = stuck & alive_gw

    # (c) Phase-1 completion (FpLeader.handlePhase1b): a classic quorum
    # of replies; k = max vote round among them; k == 1 -> that value;
    # k == 0 -> the O4 rule (a popular value — MAJ votes — must be
    # picked; argmax count is safe because a fast-committed value
    # dominates every other); no votes -> proposer 0's value.
    rec1_done = (status == I_REC1) & (n_arrived >= CQ)
    if fp.has_crash:
        rec1_done = rec1_done & prop_alive[:, None]
    any_r1 = jnp.any(arrived & (vote_round == 1), axis=0)
    # All round-1 votes in an instance carry rec_value, so "the value of
    # the max-round vote" is rec_value itself when any round-1 vote is
    # visible.
    # Exact O4 (popular_items + the leader-default branch of
    # FpLeader._handle_phase1b): pick the value with >= MAJ votes among
    # the observed round-0 votes; if NO value is popular, the leader
    # proposes its own value — proposer 0's here, since the fallback
    # runs through proposer 0 (any pick is safe: nothing can have been
    # fast-committed). Both values popular is only possible when more
    # than a bare quorum of replies arrived (then neither is committed);
    # prefer the larger count, ties toward v0.
    pick_v1 = (a_v1 >= MAJ) & ((a_v0 < MAJ) | (a_v1 > a_v0))
    popular = jnp.where(pick_v1, v1, v0)
    rec_value = jnp.where(
        rec1_done,
        jnp.where(any_r1, state.rec_value, popular),
        state.rec_value,
    )

    # (d) Phase-2 completion: CQ round-1 votes for rec_value.
    a_r1 = jnp.sum(
        arrived
        & (vote_round == 1)
        & (vote_value == state.rec_value[None, :, :]),
        axis=0,
    )
    rec2_done = (status == I_REC2) & (a_r1 >= CQ)
    if fp.has_crash:
        rec2_done = rec2_done & prop_alive[:, None]

    # ---- 5. Transitions.
    newly_chosen = fast_ok | rec2_done
    chosen_value = jnp.where(
        fast_ok, fast_val,
        jnp.where(rec2_done, state.rec_value, state.chosen_value),
    )
    chosen_fast = jnp.where(newly_chosen, fast_ok, state.chosen_fast)
    safety_violations = state.safety_violations + jnp.sum(
        newly_chosen
        & (fp_committed_value >= 0)
        & (chosen_value != fp_committed_value)
    )
    retire_at = jnp.where(newly_chosen, t + ret_lat, state.retire_at)
    status = jnp.where(newly_chosen, I_CHOSEN, status)

    # Recovery kickoff: clear stale round-0 replies, send phase 1a (the
    # message carries its phase, captured here at send time).
    status = jnp.where(stuck, I_REC1, status)
    up_arrival = jnp.where(stuck[None, :, :], INF, up_arrival)
    dn_arrival = jnp.where(stuck[None, :, :], dn_arr, dn_arrival)
    dn_phase = jnp.where(stuck[None, :, :], 1, dn_phase)
    recoveries = state.recoveries + jnp.sum(stuck)

    # Phase 1 -> phase 2: clear phase-1 replies, send phase 2a.
    status = jnp.where(rec1_done, I_REC2, status)
    up_arrival = jnp.where(rec1_done[None, :, :], INF, up_arrival)
    dn_arrival = jnp.where(rec1_done[None, :, :], dn_arr, dn_arrival)
    dn_phase = jnp.where(rec1_done[None, :, :], 2, dn_phase)

    # Stats at choice.
    lat = jnp.where(newly_chosen, t - state.issue_tick, 0)
    chosen_total = state.chosen_total + jnp.sum(newly_chosen)
    chosen_fast_total = state.chosen_fast_total + jnp.sum(fast_ok)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        newly_chosen.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )

    # ---- 6. Retire chosen instances whose decision reached the learner.
    retire = (status == I_CHOSEN) & (retire_at <= t)
    status = jnp.where(retire, I_EMPTY, status)
    clear3 = retire[None, :, :]
    acc_round = jnp.where(clear3, 0, acc_round)
    vote_round = jnp.where(clear3, -1, vote_round)
    vote_value = jnp.where(clear3, NO_VALUE, vote_value)
    up_arrival = jnp.where(clear3, INF, up_arrival)
    dn_arrival = jnp.where(clear3, INF, dn_arrival)
    dn_phase = jnp.where(clear3, 0, dn_phase)
    # Also discard the retired instance's still-in-flight round-0
    # proposals: a slow proposal firing into the slot's NEXT instance
    # would be a phantom vote for a value nobody proposed.
    p0_arrival = jnp.where(clear3, INF, p0_arrival)
    p1_arrival = jnp.where(clear3, INF, p1_arrival)
    issue_tick = jnp.where(retire, INF, state.issue_tick)
    rec_value = jnp.where(retire, NO_VALUE, rec_value)
    chosen_value_r = jnp.where(retire, NO_VALUE, chosen_value)
    chosen_fast = jnp.where(retire, False, chosen_fast)
    retire_at = jnp.where(retire, INF, retire_at)
    fp_committed_value = jnp.where(retire, NO_VALUE, fp_committed_value)
    inst_id = jnp.where(retire, -1, state.inst_id)

    # ---- 7. Issue new instances (K per group) into empty slots; with
    # conflict_rate both proposers race, else proposer 0 alone.
    empty = status == I_EMPTY
    rank = jnp.cumsum(empty.astype(jnp.int32), axis=1)
    # Workload admission (tpu/workload.py): under a shaping plan the
    # static instances_per_tick knob becomes the per-group cap.
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, G)
        adm = workload_mod.admission(wl, wls, wl_writes)
        issue = empty & (rank <= adm[:, None])
    else:
        issue = empty & (rank <= cfg.instances_per_tick)
    if fp.has_crash:
        # Dead proposers issue nothing (the workload FIFO keeps the
        # unadmitted arrivals queued — finish() sees zero admissions).
        issue = issue & prop_alive[:, None]
    count = jnp.sum(issue, axis=1)
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count,
            jnp.sum(newly_chosen, axis=1),
        )
    # Globally unique id: (per-group sequence number) * G + group.
    new_id = (state.next_inst[:, None] + rank - 1) * G + jnp.arange(
        G, dtype=jnp.int32
    )[:, None]
    inst_id = jnp.where(issue, new_id, inst_id)
    conflict_field = ((bits2 >> 0) & jnp.uint32(0xFF)).astype(jnp.int32)
    threshold = int(round(cfg.conflict_rate * 256))
    is_conflict = issue & (conflict_field < threshold)
    conflicted = jnp.where(issue, is_conflict, state.conflicted)
    conflicts_total = state.conflicts_total + jnp.sum(is_conflict)
    status = jnp.where(issue, I_FAST, status)
    issue_tick = jnp.where(issue, t, issue_tick)
    p0_send = issue[None, :, :]
    p1_send = (issue & is_conflict)[None, :, :]
    if p0_del is not None:
        # Per-acceptor fault drops/cuts on the round-0 broadcasts; the
        # recovery timeout routes a starved instance to the classic
        # (TCP) round, so loss here costs latency, never liveness.
        p0_send = p0_send & p0_del
        p1_send = p1_send & p1_del
    p0_arrival = jnp.where(p0_send, t + p0_lat, p0_arrival)
    p1_arrival = jnp.where(p1_send, t + p1_lat, p1_arrival)
    next_inst = state.next_inst + count

    # Telemetry: round-0 proposal fan-outs are the phase-2 plane (fast
    # rounds ARE phase 2); classic recoveries are the phase-1 plane.
    # "executes" counts instances leaving the ring (decision learned).
    tel = record(
        state.telemetry,
        proposals=jnp.sum(count),
        phase1_msgs=A * jnp.sum(stuck),
        phase2_msgs=A * (jnp.sum(issue) + jnp.sum(is_conflict))
        + A * jnp.sum(rec1_done),
        commits=chosen_total - state.chosen_total,
        executes=jnp.sum(retire),
        retries=recoveries - state.recoveries,
        # A revival is the recovery handoff of the crash axis — counted
        # like the other backends' recovery elections.
        leader_changes=jnp.sum(revived) if revived is not None else 0,
        queue_depth=jnp.sum(status != I_EMPTY),
        queue_capacity=G * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    return BatchedFastPaxosState(
        status=status,
        conflicted=conflicted,
        issue_tick=issue_tick,
        rec_value=rec_value,
        chosen_value=chosen_value_r,
        chosen_fast=chosen_fast,
        retire_at=retire_at,
        next_inst=next_inst,
        inst_id=inst_id,
        acc_round=acc_round,
        vote_round=vote_round,
        vote_value=vote_value,
        p0_arrival=p0_arrival,
        p1_arrival=p1_arrival,
        dn_arrival=dn_arrival,
        dn_phase=dn_phase,
        up_arrival=up_arrival,
        fp_committed_value=fp_committed_value,
        prop_alive=prop_alive,
        chosen_total=chosen_total,
        chosen_fast_total=chosen_fast_total,
        conflicts_total=conflicts_total,
        recoveries=recoveries,
        safety_violations=safety_violations,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedFastPaxosConfig,
    state: BatchedFastPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedFastPaxosState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedFastPaxosConfig, state: BatchedFastPaxosState, t
) -> dict:
    status = state.status
    # THE Fast Paxos safety property: a value that ever held a fast
    # quorum of round-0 votes is the only choosable value.
    safety_ok = state.safety_violations == 0
    # Chosen instances carry one of their two candidate values.
    v0, v1 = _values_of(state.inst_id)
    chosen = status == I_CHOSEN
    value_ok = jnp.all(
        jnp.where(
            chosen,
            (state.chosen_value == v0) | (state.chosen_value == v1),
            True,
        )
    )
    # A non-conflicted instance never needs recovery... unless its
    # timeout fired; it still must choose proposer 0's value.
    clean_value_ok = jnp.all(
        jnp.where(
            chosen & ~state.conflicted, state.chosen_value == v0, True
        )
    )
    # Vote sanity: round-1 votes only for the recovery value; acceptor
    # rounds within {0, 1}; fast counts can never choose two values.
    round_ok = jnp.all((state.acc_round >= 0) & (state.acc_round <= 1))
    books_ok = state.chosen_fast_total <= state.chosen_total
    return {
        "safety_ok": safety_ok,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "value_ok": value_ok,
        "clean_value_ok": clean_value_ok,
        "round_ok": round_ok,
        "books_ok": books_ok,
    }


def stats(cfg: BatchedFastPaxosConfig, state: BatchedFastPaxosState, t) -> dict:
    chosen = int(state.chosen_total)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (chosen + 1) // 2)).argmax())
        if chosen
        else -1
    )
    return {
        "ticks": int(t),
        "chosen": chosen,
        "chosen_fast": int(state.chosen_fast_total),
        "fast_fraction": int(state.chosen_fast_total) / max(1, chosen),
        "conflicts": int(state.conflicts_total),
        "recoveries": int(state.recoveries),
        "latency_p50_ticks": p50,
        "latency_mean_ticks": (
            float(state.lat_sum) / chosen if chosen else -1.0
        ),
        "safety_violations": int(state.safety_violations),
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedFastPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedFastPaxosConfig(
        num_groups=4, window=16, instances_per_tick=2, faults=faults,
        workload=workload,
    )
