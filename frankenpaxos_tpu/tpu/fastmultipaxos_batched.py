"""Batched Fast MultiPaxos as a single XLA program: LOG-STRUCTURED fast
rounds (reference ``fastmultipaxos/Acceptor.scala:183-238`` — every
acceptor keeps its OWN ``nextSlot`` and votes arriving client commands
into it directly; ``Leader.scala:545, 721-730`` — a fast quorum of
identical votes per slot chooses, conflicts resolve by the O4
popular-items rule in a classic round; per-actor analog
``protocols/fastmultipaxos.py``).

This differs from single-decree ``fastpaxos_batched.py`` exactly where
the reference family differs: the fast path here is a LOG — clients
broadcast commands straight to the acceptors, each acceptor appends to
its own next free slot in arrival order, and the SAME command can land
in DIFFERENT slots at different acceptors (arrival-order divergence is
the conflict source). A slot whose full acceptor census is visible
without a fast quorum goes to classic recovery; a command whose votes
all lost their slots is re-broadcast by its client (and may then be
chosen twice — the execution layer dedups, counted here as ``dups``).

TPU-first layout: [G] groups, [G, W] slot rings, [A, G, W] per-acceptor
vote state (dense: acceptor ``a`` voted EVERY slot below its
``acc_next[a]``), [G, CW] client-command rings with [A, G, CW]
broadcast arrival arrays. The fast-committed ledger records any value
that ever held a fast quorum of slot votes; choices must never
contradict it.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_latency,
    ring_retire,
)
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Slot status.
S_OPEN = 0
S_RECOVER = 1  # classic round in flight
S_CHOSEN = 2

# Command status.
C_EMPTY = 0
C_PENDING = 1
C_CHOSEN = 2

NO_VALUE = -1


@dataclasses.dataclass(frozen=True)
class BatchedFastMultiPaxosConfig:
    f: int = 1
    num_groups: int = 8  # G
    window: int = 32  # W: slot ring capacity
    cmd_window: int = 32  # CW: in-flight client commands per group
    cmds_per_tick: int = 2  # K: new client commands per group per tick
    lat_min: int = 1
    lat_max: int = 3
    # Extra per-acceptor arrival jitter (0..jitter ticks, uniform): the
    # arrival-order divergence that creates slot conflicts.
    jitter: int = 2
    recovery_timeout: int = 10  # slot age before timeout-based recovery
    retry_timeout: int = 12  # command re-broadcast period
    # Unified in-graph fault injection (tpu/faults.py): extra drops/
    # duplicates/jitter + an acceptor-axis partition on the client
    # broadcast plane (UDP semantics — the command re-broadcast timer
    # restores liveness after a heal); the classic recovery round is
    # TCP (delay-only), so a recovering slot cannot deadlock. Crash/
    # revive drives the per-group PROPOSER (the client-facing
    # sequencer): a dead proposer admits no new commands and re-sends
    # nothing, and a revival triggers a RECOVERY ELECTION — the revived
    # proposer immediately re-broadcasts every pending command (counted
    # as a leader change) while the vote plane's timeout-based classic
    # recovery clears any slots stranded mid-choose. FaultPlan.none()
    # is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-group
    # client-command admission into the command ring; completions are
    # client-observed replies. WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Kernel-layer dispatch policy (ops/registry.py): the vote plane —
    # census/pairwise-match counting, fast choose, recovery triggers,
    # the classic round, and the chosen stamps (tick steps 2-3) — routes
    # through ops.registry.dispatch as `fastmultipaxos_vote`.
    kernels: KernelPolicy = KernelPolicy()

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def quorum_majority(self) -> int:
        return (self.f + 1) // 2 + 1

    @property
    def fast_quorum(self) -> int:
        return self.f + self.quorum_majority

    def __post_init__(self):
        assert self.f >= 1
        assert self.window >= 4
        assert self.cmd_window >= 2 * self.cmds_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        assert self.jitter >= 0
        assert self.recovery_timeout >= 2 * (self.lat_max + self.jitter)
        self.faults.validate(axis=self.n)
        self.workload.validate()
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedFastMultiPaxosState:
    """Shapes: [G] groups, [G, W] slots, [A, G, W] votes, [G, CW] cmds."""

    head: jnp.ndarray  # [G] lowest non-retired slot
    acc_next: jnp.ndarray  # [A, G] each acceptor's nextSlot
    cmd_seq: jnp.ndarray  # [G] next command id (global = seq * G + g)
    prop_alive: jnp.ndarray  # [G] proposer liveness (crash/revive axis)

    # Slots.
    status: jnp.ndarray  # [G, W] S_*
    open_tick: jnp.ndarray  # [G, W] first visible vote tick (INF)
    chosen_value: jnp.ndarray  # [G, W]
    replica_arrival: jnp.ndarray  # [G, W]
    fast_committed: jnp.ndarray  # [G, W] ledger: value with an FQ of votes

    # Acceptor votes (dense below acc_next; ring-indexed by slot % W).
    vote_value: jnp.ndarray  # [A, G, W] fast-round vote (NO_VALUE none)
    vote_seen: jnp.ndarray  # [A, G, W] tick the leader sees the vote (INF)
    # Classic recovery round (round 1).
    rv_value: jnp.ndarray  # [G, W] value the classic round proposes
    rv_p2a_arrival: jnp.ndarray  # [A, G, W]
    rv_p2b_arrival: jnp.ndarray  # [A, G, W]
    rv_voted: jnp.ndarray  # [A, G, W]

    # Client commands.
    cmd_status: jnp.ndarray  # [G, CW] C_*
    cmd_id: jnp.ndarray  # [G, CW] command id (-1)
    cmd_issue: jnp.ndarray  # [G, CW] first broadcast tick
    cmd_last_send: jnp.ndarray  # [G, CW]
    cmd_arrival: jnp.ndarray  # [A, G, CW] broadcast arrival (INF)
    cmd_done_at: jnp.ndarray  # [G, CW] reply arrival after choose (INF)

    committed_slots: jnp.ndarray  # [] slots chosen
    fast_chosen: jnp.ndarray  # [] slots chosen on the fast path
    recoveries: jnp.ndarray  # [] classic recoveries started
    cmds_done: jnp.ndarray  # [] commands completed
    dups: jnp.ndarray  # [] commands chosen in more than one slot
    dropped_votes: jnp.ndarray  # [] acceptor-side ring backpressure
    safety_violations: jnp.ndarray  # [] choice contradicted the ledger
    lat_sum: jnp.ndarray  # [] command issue -> done
    lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(
    cfg: BatchedFastMultiPaxosConfig,
) -> BatchedFastMultiPaxosState:
    G, W, CW, A = cfg.num_groups, cfg.window, cfg.cmd_window, cfg.n
    return BatchedFastMultiPaxosState(
        head=jnp.zeros((G,), jnp.int32),
        acc_next=jnp.zeros((A, G), jnp.int32),
        cmd_seq=jnp.zeros((G,), jnp.int32),
        prop_alive=jnp.ones((G,), bool),
        status=jnp.zeros((G, W), DTYPE_STATUS),
        open_tick=jnp.full((G, W), INF, jnp.int32),
        chosen_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        replica_arrival=jnp.full((G, W), INF, jnp.int32),
        fast_committed=jnp.full((G, W), NO_VALUE, jnp.int32),
        vote_value=jnp.full((A, G, W), NO_VALUE, jnp.int32),
        vote_seen=jnp.full((A, G, W), INF, jnp.int32),
        rv_value=jnp.full((G, W), NO_VALUE, jnp.int32),
        rv_p2a_arrival=jnp.full((A, G, W), INF, jnp.int32),
        rv_p2b_arrival=jnp.full((A, G, W), INF, jnp.int32),
        rv_voted=jnp.zeros((A, G, W), bool),
        cmd_status=jnp.zeros((G, CW), DTYPE_STATUS),
        cmd_id=jnp.full((G, CW), -1, jnp.int32),
        cmd_issue=jnp.full((G, CW), INF, jnp.int32),
        cmd_last_send=jnp.full((G, CW), INF, jnp.int32),
        cmd_arrival=jnp.full((A, G, CW), INF, jnp.int32),
        cmd_done_at=jnp.full((G, CW), INF, jnp.int32),
        committed_slots=jnp.zeros((), jnp.int32),
        fast_chosen=jnp.zeros((), jnp.int32),
        recoveries=jnp.zeros((), jnp.int32),
        cmds_done=jnp.zeros((), jnp.int32),
        dups=jnp.zeros((), jnp.int32),
        dropped_votes=jnp.zeros((), jnp.int32),
        safety_violations=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_groups, cfg.faults
        ),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedFastMultiPaxosConfig,
    state: BatchedFastMultiPaxosState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedFastMultiPaxosState:
    G, W, CW, A = cfg.num_groups, cfg.window, cfg.cmd_window, cfg.n
    f = cfg.f
    FQ, MAJ = cfg.fast_quorum, cfg.quorum_majority
    w_iota = jnp.arange(W, dtype=jnp.int32)
    a_iota = jnp.arange(A, dtype=jnp.int32)

    k3, k2 = jax.random.split(key)
    bits3 = jax.random.bits(k3, (A, G, CW))  # [0:8) bcast lat,
    #                                [8:16) jitter, [16:24) seen lat
    bits2 = jax.random.bits(k2, (G, W))  # [0:8) rv lat, [8:16) reply lat
    bcast_lat = bit_latency(bits3, 0, cfg.lat_min, cfg.lat_max)
    jit_lat = (
        ((bits3 >> 8) & jnp.uint32(0xFF)).astype(jnp.int32)
        % (cfg.jitter + 1)
        if cfg.jitter
        else jnp.zeros((A, G, CW), jnp.int32)
    )
    seen_lat_c = bit_latency(bits3, 16, cfg.lat_min, cfg.lat_max)
    rv_lat = bit_latency(bits2, 0, cfg.lat_min, cfg.lat_max)
    reply_lat = bit_latency(bits2, 8, cfg.lat_min, cfg.lat_max)

    # Unified fault injection (tpu/faults.py): UDP semantics on the
    # client->acceptor broadcast plane (partition cuts acceptor rows;
    # the re-broadcast timer recovers), TCP delay-only on the classic
    # recovery round. none() skips all of it at trace time.
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    bcast_delivered = None
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, A)[:, None, None]
        bcast_delivered, bcast_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (A, G, CW), bcast_lat, link_up,
            rates=frates,
        )
        rv_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 1), (G, W), rv_lat, rates=frates
        )

    status = state.status
    vote_value = state.vote_value
    vote_seen = state.vote_seen

    # Proposer crash/revive (PR 3 follow-up (b)): the per-group
    # proposer is the crash axis. Guarded on has_crash so a none/
    # crash-free plan traces the exact pre-crash program.
    prop_alive = state.prop_alive
    revived = None
    if fp.has_crash:
        new_alive = faults_mod.crash_step(
            fp, faults_mod.fault_key(key, 9), prop_alive, rates=frates
        )
        revived = new_alive & ~prop_alive
        prop_alive = new_alive

    # ---- 1. Acceptors append pending command arrivals to their own
    # nextSlot in command-ring order (Acceptor.scala:229-238). Ring
    # backpressure: an acceptor whose nextSlot would overrun head + W
    # defers the arrival (it stays pending).
    pending = state.cmd_arrival <= t  # [A, G, CW]
    rank = jnp.cumsum(pending.astype(jnp.int32), axis=2)  # arrival order
    room = jnp.maximum(
        state.head[None, :] + W - state.acc_next, 0
    )  # [A, G]
    take = pending & (rank <= room[:, :, None])
    slot_of = state.acc_next[:, :, None] + rank - 1  # [A, G, CW]
    dropped_votes = state.dropped_votes + jnp.sum(pending & ~take)
    # Scatter each taken command's id into the acceptor's vote arrays.
    aa = jnp.broadcast_to(a_iota[:, None, None], (A, G, CW))
    gg = jnp.broadcast_to(jnp.arange(G)[None, :, None], (A, G, CW))
    ss = jnp.where(take, jnp.mod(slot_of, W), W)  # W = out of range
    cmd_ids3 = jnp.broadcast_to(state.cmd_id[None, :, :], (A, G, CW))
    vote_value = vote_value.at[aa, gg, ss].set(
        jnp.where(take, cmd_ids3, NO_VALUE), mode="drop"
    )
    vote_seen = vote_seen.at[aa, gg, ss].set(
        jnp.where(take, t + seen_lat_c, INF), mode="drop"
    )
    acc_next = state.acc_next + jnp.sum(take, axis=2)
    cmd_arrival = jnp.where(take, INF, state.cmd_arrival)

    # ---- 2+3. The vote plane (one registry kernel, ops/fastmultipaxos.
    # py): the leader observes per-slot vote censuses (pairwise
    # same-value counts over the tiny acceptor axis), the fast-committed
    # ledger records any value that ever held FQ actual votes (visible
    # or not), slots choose on FQ identical VISIBLE votes or fall to
    # classic recovery (full census without a fast quorum, or a timeout
    # with a quorum of the census visible — the O4 popular-items rule
    # picks best_value, which a fast-committed value always dominates),
    # the classic round's acceptor votes and f+1 quorum complete, and
    # chosen slots stamp value + replica arrival. Scalar stat counters
    # reduce the plane's masks out here.
    (
        status,
        open_tick,
        fast_committed,
        rv_value,
        rv_p2a_arrival,
        rv_p2b_arrival,
        rv_voted,
        chosen_value,
        replica_arrival,
        newly_chosen,
        fast_ok,
        start_rec,
        safety_mask,
    ) = ops_registry.dispatch(
        "fastmultipaxos_vote",
        cfg,
        vote_value,
        vote_seen,
        status,
        state.open_tick,
        state.fast_committed,
        state.rv_value,
        state.rv_p2a_arrival,
        state.rv_p2b_arrival,
        state.rv_voted,
        state.chosen_value,
        state.replica_arrival,
        rv_lat,
        reply_lat,
        t,
        fq=FQ,
        f=f,
        recovery_timeout=cfg.recovery_timeout,
    )
    recoveries = state.recoveries + jnp.sum(start_rec)
    safety_violations = state.safety_violations + jnp.sum(safety_mask)
    committed_slots = state.committed_slots + jnp.sum(newly_chosen)
    fast_chosen = state.fast_chosen + jnp.sum(fast_ok)

    # ---- 4. Command completion: a chosen slot completes its command
    # (value id -> command ring position = id // G mod CW; id = seq*G+g).
    # A second choose of the SAME id is a dup (client retry chosen
    # twice — the execution layer dedups; Leader repeated_commands).
    # For each command ring position, was it chosen this tick?
    hit = (
        newly_chosen[:, :, None]
        & (chosen_value[:, :, None] == state.cmd_id[:, None, :])
    )  # [G, W, CW]
    chosen_cmd = jnp.any(hit, axis=1)  # [G, CW]
    was_pending = state.cmd_status == C_PENDING
    newly_done = chosen_cmd & was_pending
    dups = state.dups + jnp.sum(
        chosen_cmd & (state.cmd_status == C_CHOSEN)
    )
    cmd_reply_lat = bit_latency(bits3[0], 24, cfg.lat_min, cfg.lat_max)
    cmd_status = jnp.where(newly_done, C_CHOSEN, state.cmd_status)
    cmd_done_at = jnp.where(newly_done, t + cmd_reply_lat, state.cmd_done_at)
    done_now = (cmd_status == C_CHOSEN) & (state.cmd_done_at <= t)
    cmds_done = state.cmds_done + jnp.sum(done_now)
    lat = jnp.where(done_now, t - state.cmd_issue, 0)
    lat_sum = state.lat_sum + jnp.sum(lat)
    bins = jnp.clip(lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + jax.ops.segment_sum(
        done_now.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    cmd_status = jnp.where(done_now, C_EMPTY, cmd_status)
    cmd_id = jnp.where(done_now, -1, state.cmd_id)
    cmd_issue = jnp.where(done_now, INF, state.cmd_issue)
    cmd_last_send = jnp.where(done_now, INF, state.cmd_last_send)
    cmd_done_at = jnp.where(done_now, INF, cmd_done_at)
    cmd_arrival = jnp.where(done_now[None, :, :], INF, cmd_arrival)

    # ---- 5. Retire the contiguous chosen prefix (all acceptor votes
    # and recovery state cleared; acc_next never decreases).
    pos_of_ord = jnp.mod(state.head[:, None] + w_iota[None, :], W)
    chosen_ord = (
        jnp.take_along_axis(status, pos_of_ord, axis=1) == S_CHOSEN
    ) & (
        jnp.take_along_axis(replica_arrival, pos_of_ord, axis=1) <= t
    )
    n_retire, retire_mask = ring_retire(chosen_ord, state.head)
    head = state.head + n_retire
    status = jnp.where(retire_mask, S_OPEN, status)
    open_tick = jnp.where(retire_mask, INF, open_tick)
    chosen_value = jnp.where(retire_mask, NO_VALUE, chosen_value)
    replica_arrival = jnp.where(retire_mask, INF, replica_arrival)
    fast_committed = jnp.where(retire_mask, NO_VALUE, fast_committed)
    rv_value = jnp.where(retire_mask, NO_VALUE, rv_value)
    clear3 = retire_mask[None, :, :]
    vote_value = jnp.where(clear3, NO_VALUE, vote_value)
    vote_seen = jnp.where(clear3, INF, vote_seen)
    rv_p2a_arrival = jnp.where(clear3, INF, rv_p2a_arrival)
    rv_p2b_arrival = jnp.where(clear3, INF, rv_p2b_arrival)
    rv_voted = jnp.where(clear3, False, rv_voted)

    # ---- 6. New client commands (K per group into free ring slots) +
    # retries of long-pending commands (re-broadcast; the retry may be
    # chosen in a second slot — the dup path). A dead proposer admits
    # no new commands and re-sends nothing (Leader.scala inactive
    # state); the tick it revives, it re-broadcasts EVERY pending
    # command at once — the recovery election's log-refill sweep.
    empty = cmd_status == C_EMPTY
    crank = jnp.cumsum(empty.astype(jnp.int32), axis=1)
    # Workload admission (tpu/workload.py): under a shaping plan the
    # static cmds_per_tick knob becomes the per-group admission cap.
    if wl.active:
        wl_writes, _, wls = workload_mod.begin(wl, wls, key, t, G)
        adm = workload_mod.admission(wl, wls, wl_writes)
        is_new = empty & (crank <= adm[:, None])
    else:
        is_new = empty & (crank <= cfg.cmds_per_tick)
    if fp.has_crash:
        is_new = is_new & prop_alive[:, None]
    n_new = jnp.sum(is_new, axis=1)
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, n_new, jnp.sum(done_now, axis=1)
        )
    new_id = (state.cmd_seq[:, None] + crank - 1) * G + jnp.arange(
        G, dtype=jnp.int32
    )[:, None]
    cmd_seq = state.cmd_seq + n_new
    cmd_status = jnp.where(is_new, C_PENDING, cmd_status)
    cmd_id = jnp.where(is_new, new_id, cmd_id)
    cmd_issue = jnp.where(is_new, t, cmd_issue)
    retry = (
        (cmd_status == C_PENDING)
        & ~is_new
        & (t - cmd_last_send >= cfg.retry_timeout)
    )
    if fp.has_crash:
        retry = retry & prop_alive[:, None]
        retry = retry | (
            (cmd_status == C_PENDING) & ~is_new & revived[:, None]
        )
    send = is_new | retry
    cmd_last_send = jnp.where(send, t, cmd_last_send)
    bcast_send = send[None, :, :]
    if bcast_delivered is not None:
        # Per-acceptor fault drops/cuts on the broadcast: a command all
        # of whose copies are lost is re-broadcast by its client at the
        # retry timer (and may then land in a second slot — the dup
        # path the execution layer already dedups).
        bcast_send = bcast_send & bcast_delivered
    cmd_arrival = jnp.where(
        bcast_send, t + bcast_lat + jit_lat, cmd_arrival
    )

    # Telemetry: client broadcasts straight to acceptors ARE the fast
    # (phase-2) plane; classic recoveries the phase-1 plane; acceptor
    # ring backpressure the drop counter; proposer revivals (recovery
    # elections) the leader-change counter.
    tel = record(
        state.telemetry,
        proposals=jnp.sum(n_new),
        phase1_msgs=A * (recoveries - state.recoveries),
        phase2_msgs=A * jnp.sum(send),
        commits=committed_slots - state.committed_slots,
        executes=cmds_done - state.cmds_done,
        drops=dropped_votes - state.dropped_votes,
        retries=jnp.sum(retry),
        leader_changes=jnp.sum(revived) if revived is not None else 0,
        queue_depth=jnp.sum(cmd_status != C_EMPTY),
        queue_capacity=G * CW,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    return BatchedFastMultiPaxosState(
        head=head,
        acc_next=acc_next,
        cmd_seq=cmd_seq,
        prop_alive=prop_alive,
        status=status,
        open_tick=open_tick,
        chosen_value=chosen_value,
        replica_arrival=replica_arrival,
        fast_committed=fast_committed,
        vote_value=vote_value,
        vote_seen=vote_seen,
        rv_value=rv_value,
        rv_p2a_arrival=rv_p2a_arrival,
        rv_p2b_arrival=rv_p2b_arrival,
        rv_voted=rv_voted,
        cmd_status=cmd_status,
        cmd_id=cmd_id,
        cmd_issue=cmd_issue,
        cmd_last_send=cmd_last_send,
        cmd_arrival=cmd_arrival,
        cmd_done_at=cmd_done_at,
        committed_slots=committed_slots,
        fast_chosen=fast_chosen,
        recoveries=recoveries,
        cmds_done=cmds_done,
        dups=dups,
        dropped_votes=dropped_votes,
        safety_violations=safety_violations,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        workload=wls,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedFastMultiPaxosConfig,
    state: BatchedFastMultiPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedFastMultiPaxosState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(step, (state, t0), jnp.arange(num_ticks))
    return state, t


def check_invariants(
    cfg: BatchedFastMultiPaxosConfig,
    state: BatchedFastMultiPaxosState,
    t,
) -> dict:
    # THE Fast MultiPaxos safety property: a value that ever held a fast
    # quorum of votes in a slot is the only choosable value there.
    safety_ok = state.safety_violations == 0
    # Acceptors fill densely: nextSlot never exceeds head + W.
    window_ok = jnp.all(
        (state.acc_next >= state.head[None, :])
        & (state.acc_next - state.head[None, :] <= cfg.window)
    )
    # Chosen slots carry a real command id.
    chosen = state.status == S_CHOSEN
    value_ok = jnp.all(
        jnp.where(chosen, state.chosen_value != NO_VALUE, True)
    )
    books_ok = (state.fast_chosen <= state.committed_slots) & (
        state.cmds_done <= state.committed_slots
    )
    return {
        "safety_ok": safety_ok,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "window_ok": window_ok,
        "value_ok": value_ok,
        "books_ok": books_ok,
    }


def stats(
    cfg: BatchedFastMultiPaxosConfig,
    state: BatchedFastMultiPaxosState,
    t,
) -> dict:
    done = int(state.cmds_done)
    hist = jax.device_get(state.lat_hist)
    p50 = (
        int((hist.cumsum() >= max(1, (done + 1) // 2)).argmax())
        if done
        else -1
    )
    committed = int(state.committed_slots)
    return {
        "ticks": int(t),
        "committed_slots": committed,
        "fast_fraction": int(state.fast_chosen) / max(1, committed),
        "recoveries": int(state.recoveries),
        "cmds_done": done,
        "dups": int(state.dups),
        "dropped_votes": int(state.dropped_votes),
        "safety_violations": int(state.safety_violations),
        "cmd_latency_p50_ticks": p50,
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedFastMultiPaxosConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedFastMultiPaxosConfig(
        num_groups=4, window=16, cmd_window=16, cmds_per_tick=2,
        workload=workload,
        faults=faults,
    )
