"""Shared primitives of the batched simulation backends."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

INF = jnp.int32(2**30)

# Sentinel for int16 DELTA-ENCODED clocks (see DTYPE_CLOCK below): the
# "never arrives" value of an offset clock. A plain Python int so that
# comparisons/writes stay weakly typed (bit-identical on the
# widen_state() int32 reference path).
INF16 = 2**15 - 1

LAT_BINS = 64  # histogram bins for latency stats (in ticks)

# ---------------------------------------------------------------------------
# Dtype policy (the HBM-bandwidth pass). The tick loops are elementwise
# sweeps over the whole state, so simulator throughput is set by bytes
# moved per tick, not FLOPs; arrays whose values are structurally tiny
# carry narrow dtypes so each sweep moves fewer bytes:
#
#   * DTYPE_STATUS (int8)  — slot/ring status codes and tiny phase enums
#     (a handful of named values each).
#   * DTYPE_ROUND  (int16) — ballot rounds, configuration epochs, and
#     other monotone counters that advance only on rare control events
#     (elections, reconfigurations). 32k of those per run is far beyond
#     any simulated horizon; check_invariants trips loudly before wrap
#     matters because promise monotonicity breaks first.
#   * DTYPE_COUNT  (int16) — small bounded counters (heartbeat-miss
#     ticks, clamped at their timeout by construction).
#
# Everything else keeps its width: tick/arrival clocks and INF sentinels
# are int32 (t grows without bound), value/command ids are int32 (global
# sequence numbers masked into [0, 2^31)), bool masks stay bool, and the
# stats accumulators (lat_sum, histograms, committed counters) are int32
# — narrow-dtype arithmetic widens AT the accumulation point, never
# before.
#
# The tick functions are dtype-polymorphic: they preserve whatever
# dtypes the state carries (update sites use weakly-typed Python
# scalars, never hard casts), so running the SAME tick on a
# widen_state()-upcast state reproduces the pre-narrowing int32
# semantics bit for bit — that is the reference path the dtype
# cross-validation tests pin against.
# ---------------------------------------------------------------------------
DTYPE_STATUS = jnp.int8
DTYPE_ROUND = jnp.int16
DTYPE_COUNT = jnp.int16
#   * DTYPE_CLOCK (int16) — per-message arrival clocks stored as
#     WRAP-SAFE OFFSETS from the tick counter instead of absolute
#     ticks. An offset clock holds "arrival - t" (0 = arrives this
#     tick, positive = future, bounded by lat_max + jitter ≪ 2^15),
#     INF16 = never. Every tick the whole array ages by one via
#     age_clock(), saturating at CLOCK_FLOOR so "already arrived"
#     (offset <= 0) is stable under arbitrarily long runs — the
#     wrap-safe scheme ROADMAP PR 1 follow-up (a) asked for. This
#     halves the bytes of the largest [A, G, W] arrival arrays; the
#     aging pass is one fused elementwise op on a bandwidth-bound
#     sweep that just got half as many bytes to move.
DTYPE_CLOCK = jnp.int16

# Offsets of already-arrived messages saturate here (only the sign —
# "arrived" — is ever tested again; -1 keeps `offset == 0` meaning
# "arrives exactly now" unambiguous).
CLOCK_FLOOR = -1

# ---------------------------------------------------------------------------
# Packed-plane descriptor (the policy's sub-byte tier, PR 16): State
# fields narrower than int8 that backends may carry BIT-PACKED into
# int32 words (field name -> bit width). Backends opt in per config
# (`pack_planes=True`), unpack once at tick entry and pack once at tick
# exit through tpu/packing.py — the ONLY module allowed to bit-twiddle
# these fields (the `packing-containment` analysis rule). widen_state()
# passes packed words through (already int32); the bench memory block
# prices packed vs unpacked bytes per plane from this table.
# ---------------------------------------------------------------------------
PACKED_PLANES = {
    "status": 2,  # slot ring status codes (EMPTY | PROPOSED | CHOSEN)
    "rb_status": 2,  # read-batcher ring phases (R_EMPTY..R_SENT)
    "sess_occ": 1,  # session-table occupancy bits ([L, S] liveness)
}


def age_clock(off: jnp.ndarray) -> jnp.ndarray:
    """Advance an offset clock by one tick: real offsets decrement
    (saturating at CLOCK_FLOOR), the INF16 sentinel is preserved. All
    arithmetic is weakly typed, so the widen_state() int32 reference
    path replays bit-identically."""
    return jnp.where(
        off == INF16, INF16, jnp.maximum(off - 1, CLOCK_FLOOR)
    ).astype(off.dtype)


def widen_state(state):
    """The int32 reference view of a (possibly narrowed) state pytree:
    every signed sub-32-bit integer leaf upcasts to int32; bool, uint32,
    and int32 leaves pass through. Running the same tick on the widened
    state replays the pre-policy semantics (values are unchanged — the
    policy only narrows storage), so
    ``widen_state(run(narrow)) == run(widen_state(narrow))`` bit for bit."""

    def widen(leaf):
        if (
            hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.signedinteger)
            and leaf.dtype.itemsize < 4
        ):
            return leaf.astype(jnp.int32)
        return leaf

    return jax.tree_util.tree_map(widen, state)


def state_nbytes(state) -> int:
    """Total bytes of device memory the state pytree occupies — the
    bytes one full elementwise sweep of a tick reads (and writes)."""
    return sum(
        leaf.nbytes
        for leaf in jax.tree_util.tree_leaves(state)
        if hasattr(leaf, "nbytes")
    )


def sample_latency(lat_min: int, lat_max: int, key, shape) -> jnp.ndarray:
    """Uniform per-message latency in ticks."""
    if lat_min == lat_max:
        return jnp.full(shape, lat_min, jnp.int32)
    return jax.random.randint(key, shape, lat_min, lat_max + 1)


def sample_delivered(drop_rate: float, key, shape) -> jnp.ndarray:
    """Per-message Bernoulli delivery mask."""
    if drop_rate == 0.0:
        return jnp.ones(shape, bool)
    return jax.random.uniform(key, shape) >= drop_rate


def bit_latency(
    bits: jnp.ndarray, shift: int, lat_min: int, lat_max: int
) -> jnp.ndarray:
    """Uniform latency in [lat_min, lat_max] from an 8-bit field of a
    shared random-bits array.

    Drawing independent randint arrays per message kind costs one full
    PRNG sweep each and dominates the tick on every backend (5+ sweeps
    over [G, W, A] per tick); disjoint bit fields of ONE threefry draw
    are independent, so one sweep feeds every sample. The modulo carries
    a <=1/256 bias per value — immaterial for a latency model."""
    if lat_min == lat_max:
        return jnp.full(bits.shape, lat_min, jnp.int32)
    span = lat_max - lat_min + 1
    assert span <= 256, (
        f"latency span {span} exceeds the 8-bit sample field; use "
        f"sample_latency for spans beyond 256 ticks"
    )
    field = ((bits >> shift) & jnp.uint32(0xFF)).astype(jnp.int32)
    return lat_min + field % span


def bit_delivered(
    bits: jnp.ndarray, shift: int, drop_rate
) -> jnp.ndarray:
    """Bernoulli delivery mask from an 8-bit field (loss quantized to
    multiples of 1/256 — a sim parameter, not a measured quantity).

    ``drop_rate`` is a Python float (the static path, unchanged bit for
    bit) or a TRACED float32 scalar (a ``FaultPlan(traced=True)``
    state-side rate, tpu/faults.py): the traced path applies the same
    1/256 quantization and never-round-nonzero-to-zero floor, so a
    traced rate r reproduces the static plan's mask for the same r."""
    if isinstance(drop_rate, (int, float)):
        if drop_rate == 0.0:
            return jnp.ones(bits.shape, bool)
        # Never round a requested nonzero loss down to zero loss.
        threshold = max(1, int(round(drop_rate * 256)))
        field = (bits >> shift) & jnp.uint32(0xFF)
        return field >= threshold
    q = jnp.round(drop_rate * 256.0).astype(jnp.int32)
    threshold = jnp.where(drop_rate > 0.0, jnp.maximum(q, 1), 0)
    field = ((bits >> shift) & jnp.uint32(0xFF)).astype(jnp.int32)
    return field >= threshold


def sample_quorum(
    bits: jnp.ndarray,  # [A, ...] uint32 (f==1 reads only row 0)
    shift: int,
    f: int,
    group_size: int,
    live=None,
) -> jnp.ndarray:
    """Uniform random (f+1)-of-(2f+1) member selection over the leading
    acceptor axis, from bit fields of a shared random sweep (the batched
    ThriftySystem.Random / randomReadQuorum, ThriftySystem.scala /
    QuorumSystem.scala:16-24).

    f == 1: f+1 of 3 = all but one — exclude one uniform member using the
    8-bit field of row 0 (``bits`` may then have a size-1 leading axis).
    General f: rank 16-bit score fields with the acceptor index mixed into
    the low bits, so score ties break deterministically and the quorum is
    exactly f+1 — never more.

    ``live`` (membership-aware thrifty, the lifecycle follow-up): a bool
    mask broadcastable against ``bits`` over the leading acceptor axis.
    Dead members rank strictly LAST (a high bit above the 21-bit score
    range, uniqueness preserved), so the f+1 selection samples only
    acceptors alive in the current membership whenever enough are live —
    a swapped-out acceptor no longer costs a full-group-retry round.
    With fewer than f+1 live members the selection tops up from the dead
    (their sends are membership-masked by the caller, and the slot
    correctly stalls: no live quorum exists). Requires full [A, ...]
    bits (the ranking path, even at f == 1).
    """
    A = group_size
    a_iota = jnp.arange(A, dtype=jnp.int32).reshape(
        (A,) + (1,) * (bits.ndim - 1)
    )
    if f == 1 and live is None:
        excl = (
            ((bits[0] >> shift) & jnp.uint32(0xFF)).astype(jnp.int32) % A
        )
        return a_iota != excl[None]
    assert bits.shape[0] == A
    assert A <= 32, "quorum ranking packs the acceptor index in 5 bits"
    scores = ((bits >> shift) & jnp.uint32(0xFFFF)) << 5 | a_iota.astype(
        jnp.uint32
    )
    if live is not None:
        scores = jnp.where(live, scores, scores | jnp.uint32(1 << 25))
    kth = jnp.sort(scores, axis=0)[f : f + 1]  # (f+1)-th smallest
    return scores <= kth


def ring_retire_pos(
    executable: jnp.ndarray,  # [G, W] bool, in RING-POSITION space
    ord_of_pos: jnp.ndarray,  # [G, W] ordinal of each position from head
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Retire the contiguous executable run starting at the ring head,
    computed entirely in position space: the run length is the minimum
    ordinal among non-executable positions (W if all are executable) — a
    masked min-reduction instead of a gather + prefix scan. The batched
    form of the replica's contiguous prefix execution
    (Replica.scala:394-453).

    Returns ``(n_retire [G], retire_mask [G, W])``.
    """
    W = executable.shape[-1]
    blocked = jnp.where(executable, W, ord_of_pos)
    n_retire = jnp.min(blocked, axis=-1)
    retire_mask = ord_of_pos < n_retire[..., None]
    return n_retire, retire_mask


def ring_retire(
    retire_ord: jnp.ndarray,  # [G, W] bool, in absolute order from head
    head: jnp.ndarray,  # [G]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Retire the contiguous leading run of ``retire_ord`` per ring.

    Returns ``(n_retire [G], retire_mask [G, W])`` where the mask is in
    RING-POSITION space (a position retires iff its ordinal from head is
    below the run length) — the batched form of the replica's contiguous
    prefix execution (Replica.scala:394-453) and the dependency-graph GC.
    """
    G, W = retire_ord.shape
    w_iota = jnp.arange(W, dtype=jnp.int32)
    n_retire = jnp.sum(jnp.cumprod(retire_ord.astype(jnp.int32), axis=1), axis=1)
    ord_of_pos = (w_iota[None, :] - head[:, None]) % W
    retire_mask = ord_of_pos < n_retire[:, None]
    return n_retire, retire_mask
