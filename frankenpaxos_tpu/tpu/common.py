"""Shared primitives of the batched simulation backends."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

INF = jnp.int32(2**30)

LAT_BINS = 64  # histogram bins for latency stats (in ticks)


def sample_latency(lat_min: int, lat_max: int, key, shape) -> jnp.ndarray:
    """Uniform per-message latency in ticks."""
    if lat_min == lat_max:
        return jnp.full(shape, lat_min, jnp.int32)
    return jax.random.randint(key, shape, lat_min, lat_max + 1)


def sample_delivered(drop_rate: float, key, shape) -> jnp.ndarray:
    """Per-message Bernoulli delivery mask."""
    if drop_rate == 0.0:
        return jnp.ones(shape, bool)
    return jax.random.uniform(key, shape) >= drop_rate


def ring_retire(
    retire_ord: jnp.ndarray,  # [G, W] bool, in absolute order from head
    head: jnp.ndarray,  # [G]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Retire the contiguous leading run of ``retire_ord`` per ring.

    Returns ``(n_retire [G], retire_mask [G, W])`` where the mask is in
    RING-POSITION space (a position retires iff its ordinal from head is
    below the run length) — the batched form of the replica's contiguous
    prefix execution (Replica.scala:394-453) and the dependency-graph GC.
    """
    G, W = retire_ord.shape
    w_iota = jnp.arange(W, dtype=jnp.int32)
    n_retire = jnp.sum(jnp.cumprod(retire_ord.astype(jnp.int32), axis=1), axis=1)
    ord_of_pos = (w_iota[None, :] - head[:, None]) % W
    retire_mask = ord_of_pos < n_retire[:, None]
    return n_retire, retire_mask
