"""Batched Compartmentalized MultiPaxos: every role its own array plane.

The Compartmentalization technical report (PAPERS.md, arxiv 2012.15762)
decouples every MultiPaxos bottleneck into an independently-scalable
role; HT-Paxos (arxiv 1407.1237) motivates the batching planes as the
high-throughput staging shape. This backend is that decomposition
rebuilt TPU-first — each role is a separate struct-of-arrays plane of
one compiled tick, and the role-count knobs scale the planes the way
the paper adds nodes:

  * **Batchers** (``[G, B]``): client commands accumulate at ``B``
    batchers per group (``arrivals_per_tick`` each); a full batch of
    ``batch_size`` commands ships to the leader as ONE message
    (multipaxos/Batcher.scala). The leader processes batches, not
    commands — the HT-Paxos/batching amplification: committed ENTRIES
    per tick = batches chosen x batch_size.
  * **Leader + proxy leaders** (``[G, P]``): the leader sequences a
    batch into a ring slot and hands the Phase2a broadcast to proxy
    leader ``slot % P`` (ProxyLeader.scala:190); the proxy fans out to
    the write quorum, collects Phase2b votes, and broadcasts the commit
    — the leader never touches the wide planes. Per-proxy message
    counters (``proxy_msgs``) expose the load the role absorbs; proxies
    are the crash axis of the fault plan (a dead proxy stalls exactly
    its ``slot % P`` residue class until revival).
  * **Acceptor grid** (``[R, C, G, W]``): each group's acceptors form an
    R x C grid (quorums/Grid.scala). A WRITE quorum is a random column
    transversal — one acceptor per row — and a slot is chosen when
    every row has a vote in; a READ quorum is one full row (any row
    intersects any transversal). Retries re-send to the full grid.
  * **Replicas** (``[NR, G, W]`` commits, ``[NR, G]`` watermarks):
    chosen batches broadcast to NR replicas; each replica advances its
    OWN executed watermark over the contiguous arrived prefix
    (Replica.executeLog). Replica 0 answers the client.
  * **Unbatchers / proxy replicas** (``[G, W]`` reply clocks +
    ``[G, U]`` counters): the executing replica hands the reply batch
    to unbatcher ``slot % U``, which fans the ``batch_size`` replies
    out to clients (ProxyReplica.scala). Write latency is measured
    from LEADER SEQUENCING (``propose_tick``) to the client reply —
    the consensus + execution + unbatch span; the batcher-side front
    half (accumulation, batch flight, leader-inbox wait) is kept out
    of the histogram because the pending queue carries counts, not
    per-batch identities.
  * **Read replicas** (``[NR, G, RW]``): each replica hosts a read
    batcher; a batch of ``read_rate`` reads probes a read-quorum row
    for the commit bound, then serves once the replica's own watermark
    passes it — reads scale with NR * G while never touching the write
    quorums (the paper's "reads scale with replicas" axis).

Array layout is role-major with ``(G, W)`` minor (the repo's
acceptor-major tiling rule): grid planes are ``[R, C, G, W]``, replica
planes ``[NR, G, W]`` — R/C/NR are tiny static leading axes and the
group axis shards over a device mesh (``frankenpaxos_tpu/parallel``)
with the whole write path group-local; only scalar stats and histogram
reductions cross devices.

Message clocks are wrap-safe int16 offsets (tpu/common.py DTYPE_CLOCK),
aged once per tick; ``== 0`` fires an event exactly once, ``<= 0``
tests "already arrived". Fault semantics: UDP (drop + retry) on the
Phase2a/Phase2b planes with the partition cut over the flattened R*C
grid cells, TCP (retransmit penalty) on the batcher/commit/reply
pipelines, crash/revive on the proxy-leader plane, and read probes
buffer across a cut row until the heal tick.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_CLOCK,
    DTYPE_STATUS,
    INF,
    INF16,
    LAT_BINS,
    age_clock,
    bit_latency,
)
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import elastic as elastic_mod
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
from frankenpaxos_tpu.tpu import packing
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.elastic import ElasticPlan, ElasticState
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan, LifecycleState
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Ring slot status codes (a slot holds one BATCH of batch_size commands).
EMPTY = 0
PROPOSED = 1  # Phase2a out via the slot's proxy leader
CHOSEN = 2  # write quorum formed; commit broadcast in flight


@dataclasses.dataclass(frozen=True)
class BatchedCompartmentalizedConfig:
    """Static (compile-time) parameters. Every role count is its own
    knob — the compartmentalization scaling axes."""

    num_groups: int = 4  # G: acceptor groups (the shard axis)
    grid_rows: int = 2  # R: write quorum = one acceptor per row
    grid_cols: int = 2  # C: read quorum = one full row
    num_proxy_leaders: int = 4  # P: slot s rides proxy s % P
    num_batchers: int = 2  # B batchers per group
    num_unbatchers: int = 2  # U unbatchers (proxy replicas) per group
    num_replicas: int = 3  # NR replicas (execution + read serving)
    window: int = 16  # W: in-flight batch slots per group
    batch_size: int = 4  # commands per batch (the HT-Paxos knob)
    arrivals_per_tick: int = 1  # client commands per batcher per tick
    lat_min: int = 1  # per-hop message latency (ticks, uniform)
    lat_max: int = 3
    retry_timeout: int = 8  # re-send Phase2a to the FULL grid after this
    # Read plane: each replica's read batcher forms one batch of
    # read_rate reads per tick (0 = reads off); RW ring slots pipeline
    # the probe round trips.
    read_rate: int = 0
    read_window: int = 0  # RW (0 = reads off)
    # Kernel-layer dispatch policy (ops/registry.py): the acceptor-grid
    # hot path — clock aging, column-transversal write votes,
    # every-row-voted chosen detection, the per-replica watermark
    # advance, and full-grid retry re-sends — routes through
    # ops.registry.dispatch as `compartmentalized_grid_vote` (one fused
    # Pallas pass over the [R, C, G, W] grid off the reference path;
    # group-local, so it also lowers per-device under a mesh via
    # jax.shard_map — see parallel/sharding.py).
    kernels: KernelPolicy = KernelPolicy()
    # Unified in-graph fault injection (tpu/faults.py): UDP drop/dup/
    # jitter + an R*C acceptor-cell partition on the Phase2a/Phase2b
    # planes (the leader's retry timers restore liveness after heal),
    # TCP retransmit penalties on the batcher/commit/reply pipelines,
    # crash/revive on the proxy-leader plane, and read probes defer
    # across a cut row. FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-GROUP
    # client arrivals into the batcher plane (split across the group's
    # B batchers, bounded by batcher headroom — the engine's FIFO
    # backlog replaces the batcher shed under a shaping plan); a
    # read/write mix routes the read share to the read batchers.
    # Completions are client-counted committed ENTRIES. Closed loop
    # needs closed_window >= batch_size (a lane must be able to fill a
    # batch, else a partial batch deadlocks the window).
    # WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Production-lifecycle subsystem (tpu/lifecycle.py): watermark-
    # driven window rotation (the batch-slot numbering rebases once
    # every replica's executed watermark clears the quantum — serve
    # runs of unbounded duration in a constant int32 horizon), the
    # exactly-once client session table, and the traced grid-cell
    # membership epoch axis (the serve control plane swaps a crashed
    # acceptor cell mid-run with zero recompiles; ballot-free grid
    # handoff — the full-grid retry timers re-form quorums on the new
    # membership). LifecyclePlan.none() is a structural no-op.
    lifecycle: LifecyclePlan = LifecyclePlan.none()
    # Elastic capacity (tpu/elastic.py): the paper's thesis made live —
    # each bottleneck role resizes INDEPENDENTLY behind traced
    # active-count scalars. Declarable roles: "proxies" / "unbatchers"
    # (slot-ownership moduli become `slot % min(active, target)` —
    # handoff is immediate, ownership is recomputed per tick, exactly
    # like a rotation rebase), "batchers" (the admission split narrows
    # to the live columns; a deactivating batcher's in-flight batch
    # lands first and residual partial fill migrates to batcher 0 at
    # the switch), and "replicas" (READ-serving capacity only — every
    # replica keeps executing writes, so re-activation needs no state
    # catch-up). ElasticPlan.none() is a structural no-op.
    elastic: ElasticPlan = ElasticPlan.none()
    # Bit-packed storage for the narrow hot planes (tpu/packing.py,
    # common.PACKED_PLANES): the [G, W] batch-ring status plane packs
    # 16 2-bit codes per int32 word and the [G, S] session table packs
    # a 1-bit occupancy bitmap. Pure storage transform — the tick
    # unpacks once at entry and packs once at exit, so packed runs are
    # bit-identical to unpacked runs (tests/test_packing.py).
    pack_planes: bool = False

    @property
    def acceptors_per_group(self) -> int:
        return self.grid_rows * self.grid_cols

    @property
    def num_acceptors(self) -> int:
        return self.num_groups * self.acceptors_per_group

    @property
    def rotation_alignment(self) -> int:
        """Smallest rotation shift that is an EXACT renumbering: the
        lcm of every slot-mod role assignment — ring positions (mod W),
        proxy-leader ownership (mod P), and unbatcher fan-out (mod U)."""
        return lifecycle_mod.alignment(
            self.window, self.num_proxy_leaders, self.num_unbatchers
        )

    def __post_init__(self):
        assert self.num_groups >= 1
        assert self.grid_rows >= 1 and self.grid_cols >= 1
        assert self.num_proxy_leaders >= 1
        assert self.num_batchers >= 1 and self.num_unbatchers >= 1
        assert self.num_replicas >= 1
        assert self.batch_size >= 1 and self.arrivals_per_tick >= 1
        assert self.window >= 4
        assert 1 <= self.lat_min <= self.lat_max
        assert self.retry_timeout >= 1
        # Offset clocks must hold any pending arrival: the reply chain
        # is the longest (2 hops), plus the fault plan's jitter/penalty
        # per hop.
        hop = self.lat_max + self.faults.jitter + self.faults.drop_penalty
        assert 2 * hop < INF16
        if self.read_rate:
            assert self.read_window >= 2, "read ring needs >= 2 slots"
        else:
            assert self.read_window == 0
        self.faults.validate(axis=self.acceptors_per_group)
        self.workload.validate(reads_supported=self.read_rate > 0)
        self.lifecycle.validate(align=self.rotation_alignment)
        self.elastic.validate(
            {
                "proxies": self.num_proxy_leaders,
                "batchers": self.num_batchers,
                "unbatchers": self.num_unbatchers,
                "replicas": self.num_replicas,
            }
        )
        if self.elastic.active:
            # The batcher admission split (and the SLO signals that
            # drive resizes) live on the workload engine's cap.
            assert self.workload.active, (
                "compartmentalized elastic roles need an active "
                "workload plan (the admission split is the resize "
                "surface)"
            )
        if self.workload.closed:
            assert self.workload.closed_window >= self.batch_size, (
                "compartmentalized closed loop needs closed_window >= "
                "batch_size (a partial batch would strand the window)"
            )
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedCompartmentalizedState:
    """Struct-of-arrays cluster state, one plane per role (module
    docstring). Shapes: [G] groups, [G, W] batch ring, [G, B] batchers,
    [G, P] proxies, [G, U] unbatchers, [R, C, G, W] acceptor grid,
    [NR, G, *] replicas."""

    # Batcher plane.
    bat_fill: jnp.ndarray  # [G, B] commands accumulated (< 2*batch_size)
    bat_arrival: jnp.ndarray  # [G, B] batch->leader offset clock (INF16)
    bat_shed: jnp.ndarray  # [] commands shed by batcher backpressure
    pending: jnp.ndarray  # [G] batches at the leader awaiting a ring slot

    # Leader / batch ring.
    next_slot: jnp.ndarray  # [G] next per-group batch sequence number
    head: jnp.ndarray  # [G] lowest non-retired batch slot
    status: jnp.ndarray  # [G, W] EMPTY | PROPOSED | CHOSEN
    propose_tick: jnp.ndarray  # [G, W] proposal tick (latency base)
    last_send: jnp.ndarray  # [G, W] last Phase2a send tick (retries)

    # Proxy-leader plane.
    proxy_alive: jnp.ndarray  # [G, P] liveness (crash/revive axis)
    proxy_msgs: jnp.ndarray  # [G, P] messages handled per proxy (load)

    # Acceptor grid (offset clocks).
    p2a_arrival: jnp.ndarray  # [R, C, G, W] Phase2a offset clock (INF16)
    p2b_arrival: jnp.ndarray  # [R, C, G, W] Phase2b offset clock at proxy

    # Replica plane.
    rep_arrival: jnp.ndarray  # [NR, G, W] commit-broadcast offset clock
    rep_exec: jnp.ndarray  # [NR, G] per-replica executed watermark (slots)

    # Unbatcher / client completion.
    reply_arrival: jnp.ndarray  # [G, W] reply-chain offset clock (INF16)
    unbat_msgs: jnp.ndarray  # [G, U] reply batches fanned per unbatcher

    # Read plane (all zero-sized when read_window == 0).
    rd_issue: jnp.ndarray  # [NR, G, RW] batch formation tick (INF = free)
    rd_bound: jnp.ndarray  # [NR, G, RW] commit-prefix bound (slot count)
    rd_count: jnp.ndarray  # [NR, G, RW] reads carried by the batch
    rd_probe: jnp.ndarray  # [NR, G, RW] read-quorum probe offset clock
    rd_row: jnp.ndarray  # [NR, G, RW] probed grid row (partition defer)

    # Stats (entries = commands; a batch is batch_size entries).
    committed: jnp.ndarray  # [] entries in chosen batches (cumulative)
    batches_committed: jnp.ndarray  # [] batches chosen (cumulative)
    retired: jnp.ndarray  # [] batches retired (cumulative)
    writes_done: jnp.ndarray  # [] entries fully round-tripped to clients
    lat_sum: jnp.ndarray  # [] entry-weighted client write latency sum
    lat_hist: jnp.ndarray  # [LAT_BINS] client write latency histogram
    reads_done: jnp.ndarray  # [] reads served (cumulative)
    reads_shed: jnp.ndarray  # [] reads shed by read-batcher backpressure
    read_lat_sum: jnp.ndarray  # [] read-weighted latency sum
    read_lat_hist: jnp.ndarray  # [LAT_BINS] read latency histogram
    workload: WorkloadState  # shaping state (tpu/workload.py)
    # Production-lifecycle state (tpu/lifecycle.py: rotation counters,
    # the [G, S] session table, the traced [R, C, G] grid membership
    # mask + epoch; all-empty under LifecyclePlan.none()).
    lifecycle: LifecycleState
    # Elastic-capacity state (tpu/elastic.py: traced active/target
    # role counts + resize books; all-empty under ElasticPlan.none()).
    elastic: ElasticState

    # Device-side per-tick metric ring (tpu/telemetry.py contract).
    telemetry: Telemetry


def _pack_status(cfg, plane: jnp.ndarray) -> jnp.ndarray:
    """Storage form of a status plane under this config's policy."""
    return packing.pack_status(plane) if cfg.pack_planes else plane


def _unpack_status(cfg, words: jnp.ndarray, size: int) -> jnp.ndarray:
    """Compute form (the int8 twin) of a stored status plane."""
    return packing.unpack_status(words, size) if cfg.pack_planes else words


def init_state(
    cfg: BatchedCompartmentalizedConfig,
) -> BatchedCompartmentalizedState:
    G, W = cfg.num_groups, cfg.window
    R, C = cfg.grid_rows, cfg.grid_cols
    P, B, U = cfg.num_proxy_leaders, cfg.num_batchers, cfg.num_unbatchers
    NR, RW = cfg.num_replicas, cfg.read_window
    return BatchedCompartmentalizedState(
        bat_fill=jnp.zeros((G, B), jnp.int32),
        bat_arrival=jnp.full((G, B), INF16, DTYPE_CLOCK),
        bat_shed=jnp.zeros((), jnp.int32),
        pending=jnp.zeros((G,), jnp.int32),
        next_slot=jnp.zeros((G,), jnp.int32),
        head=jnp.zeros((G,), jnp.int32),
        status=_pack_status(cfg, jnp.zeros((G, W), DTYPE_STATUS)),
        propose_tick=jnp.full((G, W), INF, jnp.int32),
        last_send=jnp.full((G, W), INF, jnp.int32),
        proxy_alive=jnp.ones((G, P), bool),
        proxy_msgs=jnp.zeros((G, P), jnp.int32),
        p2a_arrival=jnp.full((R, C, G, W), INF16, DTYPE_CLOCK),
        p2b_arrival=jnp.full((R, C, G, W), INF16, DTYPE_CLOCK),
        rep_arrival=jnp.full((NR, G, W), INF16, DTYPE_CLOCK),
        rep_exec=jnp.zeros((NR, G), jnp.int32),
        reply_arrival=jnp.full((G, W), INF16, DTYPE_CLOCK),
        unbat_msgs=jnp.zeros((G, U), jnp.int32),
        rd_issue=jnp.full((NR, G, RW), INF, jnp.int32),
        rd_bound=jnp.full((NR, G, RW), -1, jnp.int32),
        rd_count=jnp.zeros((NR, G, RW), jnp.int32),
        rd_probe=jnp.full((NR, G, RW), INF16, DTYPE_CLOCK),
        rd_row=jnp.zeros((NR, G, RW), jnp.int32),
        committed=jnp.zeros((), jnp.int32),
        batches_committed=jnp.zeros((), jnp.int32),
        retired=jnp.zeros((), jnp.int32),
        writes_done=jnp.zeros((), jnp.int32),
        lat_sum=jnp.zeros((), jnp.int32),
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        reads_done=jnp.zeros((), jnp.int32),
        reads_shed=jnp.zeros((), jnp.int32),
        read_lat_sum=jnp.zeros((), jnp.int32),
        read_lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_groups, cfg.faults
        ),
        lifecycle=lifecycle_mod.make_state(
            cfg.lifecycle, G, acceptor_shape=(R, C, G),
            packed=cfg.pack_planes,
        ),
        elastic=elastic_mod.make_state(cfg.elastic),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedCompartmentalizedConfig,
    state: BatchedCompartmentalizedState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedCompartmentalizedState:
    G, W = cfg.num_groups, cfg.window
    R, C = cfg.grid_rows, cfg.grid_cols
    P, B, U = cfg.num_proxy_leaders, cfg.num_batchers, cfg.num_unbatchers
    NR, RW = cfg.num_replicas, cfg.read_window
    BS = cfg.batch_size
    fp = cfg.faults
    w_iota = jnp.arange(W, dtype=jnp.int32)
    # Packed storage: unpack ONCE into the int8 plane every tick
    # equation (and the grid-vote kernel) reads; re-packed at the
    # write-back below. The unpacked twin reads the same array.
    status_in = _unpack_status(cfg, state.status, W)

    # 0. Age the narrow offset clocks by one tick ("fires now" is == 0,
    # "already arrived" is <= 0). The WIDE planes — the [R, C, G, W]
    # grid clocks and the [NR, G, W] commit broadcast — age inside the
    # grid-vote plane below (ops/compartmentalized.py), so off the
    # reference path they are read from HBM exactly once per tick.
    bat_arrival = age_clock(state.bat_arrival)
    reply_arrival = age_clock(state.reply_arrival)
    rd_probe = age_clock(state.rd_probe) if RW else state.rd_probe

    # PRNG sweeps: one threefry draw per plane family, bit-packed fields
    # (tpu/common.py idiom). Grid sweep fields: [0:8) p2a leg latency,
    # [8:16) p2b leg, [16:24) retry, [24:32) column transversal choice.
    k_grid, k_rep, k_misc, k_read = jax.random.split(key, 4)
    bits_grid = jax.random.bits(k_grid, (R, C, G, W))
    p2a_lat = bit_latency(bits_grid, 0, cfg.lat_min, cfg.lat_max)
    p2b_lat = bit_latency(bits_grid, 8, cfg.lat_min, cfg.lat_max)
    retry_lat = bit_latency(bits_grid, 16, cfg.lat_min, cfg.lat_max)
    # One quorum column per (row, group, slot): the write transversal.
    q_col = (
        ((bits_grid[:, 0] >> 24) & jnp.uint32(0xFF)).astype(jnp.int32) % C
    )  # [R, G, W]
    # Replica sweep: [0:8) commit-broadcast leg, [8:16) reply chain leg
    # (row 0), [16:24) reply chain second hop (row 0).
    bits_rep = jax.random.bits(k_rep, (NR, G, W))
    rep_lat = bit_latency(bits_rep, 0, cfg.lat_min, cfg.lat_max)
    reply_lat = bit_latency(bits_rep[0], 8, cfg.lat_min, cfg.lat_max) + (
        bit_latency(bits_rep[0], 16, cfg.lat_min, cfg.lat_max)
    )  # [G, W]: replica->unbatcher + unbatcher->client
    # Batcher sweep: [0:8) batch->leader latency.
    bits_bat = jax.random.bits(k_misc, (G, B))
    bat_lat = bit_latency(bits_bat, 0, cfg.lat_min, cfg.lat_max)

    # Fault transforms (structural no-ops under FaultPlan.none()).
    # UDP on the grid planes: extra drop/dup/jitter + the R*C cell cut;
    # TCP (retransmit penalties) on the batcher/commit/reply pipelines.
    p2a_del = jnp.ones((R, C, G, W), bool)
    p2b_del = jnp.ones((R, C, G, W), bool)
    retry_del = jnp.ones((R, C, G, W), bool)
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    if fp.messages_active:
        kf = faults_mod.fault_key(key)
        link_up = faults_mod.partition_row(fp, t, R * C).reshape(R, C, 1, 1)
        p2a_del, p2a_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 0), (R, C, G, W), p2a_lat, link_up,
            rates=frates,
        )
        p2b_del, p2b_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 1), (R, C, G, W), p2b_lat, link_up,
            rates=frates,
        )
        retry_del, retry_lat = faults_mod.message_faults(
            fp, jax.random.fold_in(kf, 2), (R, C, G, W), retry_lat, link_up,
            rates=frates,
        )
    if fp.active:
        kf = faults_mod.fault_key(key, 1)
        bat_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 0), (G, B), bat_lat, rates=frates
        )
        rep_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 1), (NR, G, W), rep_lat,
            rates=frates,
        )
        reply_lat = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 2), (G, W), reply_lat, rates=frates
        )

    # 1. Proxy-leader crash/revive (the role's fault axis).
    proxy_alive = state.proxy_alive
    if fp.has_crash:
        proxy_alive = faults_mod.crash_step(
            fp, faults_mod.fault_key(key, 2), proxy_alive, rates=frates
        )

    # 1.5 Elastic capacity (tpu/elastic.py): apply any pending role
    # resize, then route this tick's work over the live instances.
    # Scale-up is a mask flip; scale-down waits for the deactivating
    # tail to drain (batchers: no in-flight batch; read replicas: no
    # in-flight read batch; proxies/unbatchers hand off immediately —
    # slot ownership is recomputed per tick, like a rotation rebase).
    ela = cfg.elastic
    els = state.elastic
    n_resized = 0
    bat_fill = state.bat_fill
    if ela.active:
        drained = {}
        if ela.declares("batchers"):
            b_cols = jnp.arange(B, dtype=jnp.int32)[None, :]
            b_tgt = elastic_mod.target_count(ela, els, "batchers", B)
            drained["batchers"] = jnp.all(
                jnp.where(
                    b_cols >= b_tgt, state.bat_arrival == INF16, True
                )
            )
        if ela.declares("replicas") and RW:
            nr_col = jnp.arange(NR, dtype=jnp.int32)[:, None, None]
            nr_tgt = elastic_mod.target_count(ela, els, "replicas", NR)
            drained["replicas"] = jnp.all(
                jnp.where(nr_col >= nr_tgt, state.rd_issue >= INF, True)
            )
        old_b = elastic_mod.count(ela, els, "batchers", B)
        els, n_resized = elastic_mod.apply(ela, els, drained)
        if ela.declares("batchers"):
            # Residual partial fill of batchers freed THIS tick
            # migrates to batcher 0: the commands were already admitted
            # (client-counted), so conservation needs them to batch.
            new_b = elastic_mod.count(ela, els, "batchers", B)
            b_cols = jnp.arange(B, dtype=jnp.int32)[None, :]
            freed = (b_cols >= new_b) & (b_cols < old_b)
            mig = jnp.where(freed, bat_fill, 0)
            bat_fill = (bat_fill - mig).at[:, 0].add(
                jnp.sum(mig, axis=1)
            )
    # Slot-ownership moduli for this tick (static P/U when the role is
    # not elastic — the exact pre-elastic program).
    p_mod = elastic_mod.routing_count(ela, els, "proxies", P)
    u_mod = elastic_mod.routing_count(ela, els, "unbatchers", U)

    # 2. Batchers: admit client commands (shed past 2*batch_size — the
    # batcher's own backpressure), receive fired batches at the leader,
    # and ship full batches (one message each) when idle and the leader
    # inbox has room.
    cap = 2 * BS
    if wl.active:
        # Workload admission (tpu/workload.py): the engine's per-group
        # cap splits across the group's live batchers, bounded by
        # batcher headroom; residual demand stays in the engine's FIFO
        # backlog (the engine sheds at its own bound, so bat_shed
        # stays 0).
        wl_writes, wl_reads, wls = workload_mod.begin(wl, wls, key, t, G)
        adm = workload_mod.admission(wl, wls, wl_writes)  # [G]
        b_iota = jnp.arange(B, dtype=jnp.int32)[None, :]
        if ela.declares("batchers"):
            b_act = elastic_mod.routing_count(ela, els, "batchers", B)
            want_b = jnp.where(
                b_iota < b_act,
                (adm // b_act)[:, None]
                + (b_iota < (adm % b_act)[:, None]),
                0,
            )
        else:
            want_b = (adm // B)[:, None] + (b_iota < (adm % B)[:, None])
        take_b = jnp.minimum(want_b, cap - bat_fill)
        fill = bat_fill + take_b
        adm_g = jnp.sum(take_b, axis=1)  # [G] actual entries admitted
        admitted = jnp.sum(adm_g)
        bat_shed = state.bat_shed
    else:
        fill = bat_fill + cfg.arrivals_per_tick
        shed = jnp.maximum(fill - cap, 0)
        fill = fill - shed
        admitted = G * B * cfg.arrivals_per_tick - jnp.sum(shed)
        bat_shed = state.bat_shed + jnp.sum(shed)
    fired_b = bat_arrival == 0  # batch lands at the leader now
    pending = state.pending + jnp.sum(fired_b, axis=1)
    bat_arrival = jnp.where(fired_b, INF16, bat_arrival)
    can_emit = (
        (fill >= BS)
        & (bat_arrival == INF16)
        & (state.pending < B)[:, None]
    )
    bat_arrival = jnp.where(
        can_emit, bat_lat.astype(bat_arrival.dtype), bat_arrival
    )
    fill = jnp.where(can_emit, fill - BS, fill)

    # 2.5 Traced grid-cell reconfiguration (tpu/lifecycle.py): the
    # membership mask + epoch live in state, steered by the serve
    # control plane with zero recompiles. This backend's handoff is
    # BALLOT-FREE (the grid has no rounds): on an epoch switch,
    # departed cells' pending Phase2as clear every tick (they never
    # receive again — the mask also gates the new-send/retry planes)
    # and their in-flight votes on UNCHOSEN slots drop, so the
    # full-grid retry timers re-form each quorum on the live cells —
    # the visible commit dip-and-recover. Chosen slots keep their
    # old-epoch vote records until retirement (quorum certificates
    # stay intact); the old epoch GCs behind the lifecycle watermark.
    lc = cfg.lifecycle
    lcs = state.lifecycle
    p2a_state = state.p2a_arrival
    p2b_state = state.p2b_arrival
    cell_mask = None
    if lc.reconfig:
        lc_switch = lifecycle_mod.reconfig_switch(lc, lcs)
        lcs = lifecycle_mod.reconfig_applied(
            lc, lcs, lc_switch, state.next_slot, state.head
        )
        cell_mask = lcs.acc_mask  # [R, C, G], post-switch
        not_member = ~cell_mask[:, :, :, None]
        p2a_state = jnp.where(not_member, INF16, p2a_state)
        p2b_state = jnp.where(
            lc_switch & not_member & (status_in != CHOSEN)[None, None],
            INF16,
            p2b_state,
        )
        retry_del = retry_del & cell_mask[:, :, :, None]

    # 3-5 + 9. The acceptor-grid HOT PATH as one registry plane
    # (ops/compartmentalized.py `compartmentalized_grid_vote`): aging
    # of the grid + commit-broadcast clocks, acceptor votes on Phase2a
    # arrivals (idempotent Phase2b min-write), the every-row-voted
    # column-transversal quorum gated on the slot's proxy being alive,
    # the commit broadcast arming + per-replica watermark advance, and
    # the full-grid retry re-send of timed-out PROPOSED slots. Off the
    # reference path this is ONE Pallas grid program per tick (the two
    # [R, C, G, W] arrays are read from HBM once); the reference twin
    # is exactly this composition in pure jnp, so kernel-vs-reference
    # bit-identity doubles as fused-vs-unfused bit-identity. The retry
    # half runs BEFORE retirement/sequencing here where the old tick
    # ran it after — the write masks are disjoint (retries touch only
    # slots that stay PROPOSED), so the composition is bit-identical.
    s_of_pos = state.head[:, None] + (w_iota[None, :] - state.head[:, None]) % W
    p_of_pos = s_of_pos % p_mod  # [G, W] proxy owning each ring position
    alive_of_pos = jnp.take_along_axis(proxy_alive, p_of_pos, axis=1)
    (
        p2a_arrival,
        p2b_arrival,
        rep_arrival,
        status,
        last_send,
        rep_exec,
        newly_chosen,
        timed_out,
        votes_cast,
        votes_dropped,
    ) = ops_registry.dispatch(
        "compartmentalized_grid_vote",
        cfg,
        p2a_state,
        p2b_state,
        state.rep_arrival,
        status_in,
        state.last_send,
        state.rep_exec,
        state.head,
        state.next_slot,
        alive_of_pos,
        p2b_del,
        retry_del,
        p2b_lat,
        retry_lat,
        rep_lat,
        t,
        retry_timeout=cfg.retry_timeout,
    )
    n_chosen = jnp.sum(newly_chosen)
    batches_committed = state.batches_committed + n_chosen
    committed = state.committed + BS * n_chosen
    if wl.active:
        # Completions: committed ENTRIES per group (batches x BS — the
        # client-counted unit the batchers admitted).
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, adm_g,
            BS * jnp.sum(newly_chosen, axis=1),
        )
    ord_of_pos = (w_iota[None, :] - state.head[:, None]) % W  # [G, W]

    # 6. Replica 0 hands newly-executed batches to the unbatcher, which
    # fans replies to clients (one combined 2-hop clock).
    exec0_ord = (rep_exec[0] - state.head)  # [G] prefix length, replica 0
    newly_exec0 = (
        (ord_of_pos < exec0_ord[:, None])
        & (reply_arrival == INF16)
        & (status == CHOSEN)
    )
    reply_arrival = jnp.where(
        newly_exec0, reply_lat.astype(reply_arrival.dtype), reply_arrival
    )
    # Client completion: the reply lands — entry-weighted latency.
    replied_now = reply_arrival == 0
    n_replied = jnp.sum(replied_now)
    writes_done = state.writes_done + BS * n_replied
    w_lat = jnp.where(replied_now, t - state.propose_tick, 0)
    lat_sum = state.lat_sum + BS * jnp.sum(w_lat)
    bins = jnp.clip(w_lat, 0, LAT_BINS - 1)
    lat_hist = state.lat_hist + BS * jax.ops.segment_sum(
        replied_now.astype(jnp.int32).ravel(), bins.ravel(), LAT_BINS
    )
    # Unbatcher load accounting (one-hot over U: stays group-local
    # under the mesh, unlike a flattened scatter-add).
    u_of_pos = s_of_pos % u_mod
    unbat_msgs = state.unbat_msgs + jnp.sum(
        replied_now[:, :, None]
        & (u_of_pos[:, :, None] == jnp.arange(U, dtype=jnp.int32)),
        axis=1,
    )

    # 7. Retire the contiguous prefix that every replica executed AND
    # whose client reply has landed.
    min_exec_ord = jnp.min(rep_exec, axis=0) - state.head  # [G]
    done_pos = (
        (ord_of_pos < min_exec_ord[:, None])
        & (reply_arrival <= 0)
        & (status == CHOSEN)
    )
    n_retire = jnp.min(
        jnp.where(done_pos, W, ord_of_pos), axis=1
    )  # first not-done ordinal
    retire = ord_of_pos < n_retire[:, None]
    head = state.head + n_retire
    retired = state.retired + jnp.sum(n_retire)
    status = jnp.where(retire, EMPTY, status)
    propose_tick = jnp.where(retire, INF, state.propose_tick)
    last_send = jnp.where(retire, INF, last_send)
    reply_arrival = jnp.where(retire, INF16, reply_arrival)
    p2a_arrival = jnp.where(retire[None, None], INF16, p2a_arrival)
    p2b_arrival = jnp.where(retire[None, None], INF16, p2b_arrival)
    rep_arrival = jnp.where(retire[None], INF16, rep_arrival)

    # 8. Leader sequences pending batches into free ring slots and
    # hands the Phase2a broadcast to proxy `slot % P` — sent to the
    # write transversal (one acceptor per row) when the proxy is alive.
    space = W - (state.next_slot - head)
    k_new = jnp.minimum(pending, space)
    delta = (w_iota[None, :] - state.next_slot[:, None]) % W
    is_new = delta < k_new[:, None]
    pending = pending - k_new
    next_slot = state.next_slot + k_new
    status = jnp.where(is_new, PROPOSED, status)
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    # Recompute slot->proxy for the NEW occupancy (positions beyond the
    # old next_slot now hold fresh slots).
    s_of_pos = head[:, None] + (w_iota[None, :] - head[:, None]) % W
    p_of_pos = s_of_pos % p_mod
    alive_of_pos = jnp.take_along_axis(proxy_alive, p_of_pos, axis=1)
    in_quorum = (
        jnp.arange(C, dtype=jnp.int32)[None, :, None, None]
        == q_col[:, None, :, :]
    )  # [R, C, G, W]
    send = (is_new & alive_of_pos)[None, None] & in_quorum
    if cell_mask is not None:
        # Membership gating: fresh Phase2as reach live cells only. A
        # transversal that sampled a departed cell leaves its row
        # unvoted until the full-grid retry re-forms the quorum.
        send = send & cell_mask[:, :, :, None]
    p2a_arrival = jnp.where(
        send & p2a_del, p2a_lat.astype(p2a_arrival.dtype), p2a_arrival
    )

    # (Step 9, proxy retries, now lives inside the grid-vote plane:
    # timed-out PROPOSED slots already re-broadcast to the full grid
    # and stamped last_send = t before retirement/sequencing — the
    # masks are disjoint from retire/is_new, so the order commutes.)

    # Proxy load accounting (one-hot over P, group-local).
    p_onehot = p_of_pos[:, :, None] == jnp.arange(P, dtype=jnp.int32)
    per_pos_msgs = (
        R * is_new.astype(jnp.int32)  # transversal Phase2a
        + (R * C) * timed_out.astype(jnp.int32)  # full-grid retry
        + votes_cast  # Phase2b votes collected
        + NR * newly_chosen.astype(jnp.int32)  # commit broadcast
    )
    proxy_msgs = state.proxy_msgs + jnp.sum(
        per_pos_msgs[:, :, None] * p_onehot, axis=1
    )

    # 10. Read plane: each replica's read batcher forms one batch per
    # tick, probes a read-quorum row for the commit-prefix bound, and
    # serves once its OWN watermark passes the bound.
    reads_done = state.reads_done
    reads_shed = state.reads_shed
    read_lat_sum = state.read_lat_sum
    read_lat_hist = state.read_lat_hist
    rd_issue, rd_bound = state.rd_issue, state.rd_bound
    rd_count, rd_row = state.rd_count, state.rd_row
    probes_sent = jnp.zeros((), jnp.int32)
    if RW:
        bits_read = jax.random.bits(k_read, (NR, G, RW))
        probe_lat = bit_latency(bits_read, 0, cfg.lat_min, cfg.lat_max) + (
            bit_latency(bits_read, 8, cfg.lat_min, cfg.lat_max)
        )
        probe_row = (
            ((bits_read >> 16) & jnp.uint32(0xFF)).astype(jnp.int32) % R
        )
        if fp.active:
            probe_lat = faults_mod.tcp_latency(
                fp, faults_mod.fault_key(key, 3), (NR, G, RW), probe_lat,
                rates=frates,
            )
        if fp.has_partition:
            # An in-flight probe to a row with any cut cell buffers to
            # the heal tick (TCP read-quorum semantics): re-deferred
            # every tick the cut is active, so it can never fire early.
            sides = jnp.asarray(fp.partition, jnp.int32).reshape(R, C)
            row_cut_static = jnp.any(sides == 1, axis=1)  # [R]
            in_flight = (rd_issue < INF) & (rd_probe > 0)
            cut = (
                row_cut_static[rd_row]
                & in_flight
                & faults_mod.partition_active(fp, t)
            )
            rd_probe = faults_mod.defer_to_heal_offset(
                fp, rd_probe, cut, t
            )
        # Serve: probe returned and the replica's watermark passed the
        # bound (bound is a commit-prefix slot count; every slot below
        # it is chosen, so execution reaches it).
        served = (
            (rd_issue < INF)
            & (rd_probe <= 0)
            & (rep_exec[:, :, None] >= rd_bound)
        )
        n_served = jnp.sum(jnp.where(served, rd_count, 0))
        reads_done = reads_done + n_served
        r_lat = jnp.where(served, t - rd_issue, 0)
        read_lat_sum = read_lat_sum + jnp.sum(
            jnp.where(served, rd_count * r_lat, 0)
        )
        r_bins = jnp.clip(r_lat, 0, LAT_BINS - 1)
        # Transpose the sharded group axis to the FRONT before
        # linearizing: reshaping [NR, G, RW] with G sharded in the
        # middle would force an all-gather, while [G, NR, RW] -> flat
        # partitions into contiguous per-device blocks.
        read_lat_hist = read_lat_hist + jax.ops.segment_sum(
            jnp.where(served, rd_count, 0).transpose(1, 0, 2).ravel(),
            r_bins.transpose(1, 0, 2).ravel(),
            LAT_BINS,
        )
        rd_issue = jnp.where(served, INF, rd_issue)
        rd_bound = jnp.where(served, -1, rd_bound)
        rd_count = jnp.where(served, 0, rd_count)
        # Form one new batch per (replica, group): first free ring slot.
        free = rd_issue >= INF
        rank = jnp.cumsum(free.astype(jnp.int32), axis=2)
        form = free & (rank == 1)
        any_free = jnp.any(free, axis=2)
        if wl.has_reads:
            # Workload read mix: the group's read arrivals split across
            # its LIVE read batchers (replicas keep executing writes
            # when elastically deactivated — only read serving
            # narrows); empty shares form no batch.
            nr_iota = jnp.arange(NR, dtype=jnp.int32)[:, None]
            if ela.declares("replicas"):
                nr_act = elastic_mod.routing_count(
                    ela, els, "replicas", NR
                )
                rcount = jnp.where(
                    nr_iota < nr_act,
                    (wl_reads // nr_act)[None, :]
                    + (nr_iota < (wl_reads % nr_act)[None, :]),
                    0,
                )  # [NR, G]
            else:
                rcount = (wl_reads // NR)[None, :] + (
                    nr_iota < (wl_reads % NR)[None, :]
                )  # [NR, G]
            form = form & (rcount[:, :, None] > 0)
            reads_shed = reads_shed + jnp.sum(
                jnp.where(~any_free, rcount, 0)
            )
        else:
            if ela.declares("replicas"):
                # Static read batches form on live replicas only.
                nr_iota = jnp.arange(NR, dtype=jnp.int32)[:, None]
                nr_act = elastic_mod.routing_count(
                    ela, els, "replicas", NR
                )
                form = form & (nr_iota[:, :, None] < nr_act)
            reads_shed = reads_shed + cfg.read_rate * jnp.sum(~any_free)
        # The bound: this group's chosen-prefix watermark (every slot
        # below it is chosen) — what the read-quorum row reports.
        # Ordinals are recomputed against the POST-RETIREMENT head
        # (ord_of_pos is ordinal space of the old head — on a tick that
        # retires, mixing it with the new head/status would collapse
        # the bound to the new head); positions beyond the live range
        # read as gaps, capping the prefix at the allocated frontier.
        ord_now = (w_iota[None, :] - head[:, None]) % W
        chosen_prefix = jnp.min(
            jnp.where(
                (status == CHOSEN)
                & (ord_now < (next_slot - head)[:, None]),
                W,
                ord_now,
            ),
            axis=1,
        )
        pw = head + chosen_prefix  # [G]
        rd_issue = jnp.where(form, t, rd_issue)
        rd_bound = jnp.where(form, pw[None, :, None], rd_bound)
        if wl.has_reads:
            rd_count = jnp.where(form, rcount[:, :, None], rd_count)
        else:
            rd_count = jnp.where(form, cfg.read_rate, rd_count)
        rd_row = jnp.where(form, probe_row, rd_row)
        rd_probe = jnp.where(
            form, probe_lat.astype(rd_probe.dtype), rd_probe
        )
        probes_sent = C * jnp.sum(form)

    # 10.5 Production lifecycle (tpu/lifecycle.py). Session table:
    # this tick's client-counted committed ENTRIES (batches x BS — the
    # same quantity the workload engine's finish() receives) record
    # into the [G, S] table; duplicate re-submissions answer from the
    # cache on a disjoint PRNG stream, never entering the batcher
    # plane. Rotation: the shift is computed here (post-retirement
    # head) so the telemetry row records it and the span sampler stays
    # on the pre-roll base; the slot planes rebase at tick end.
    if lc.has_sessions:
        lcs = lifecycle_mod.sessions_step(
            lc, lcs, key, t, BS * jnp.sum(newly_chosen, axis=1)
        )
    lc_shift = None
    lc_base = 0
    if lc.compaction:
        lc_base = lcs.rot_base
        lc_shift, lcs = lifecycle_mod.rotation_shift(
            lc, lcs, jnp.min(head), cfg.rotation_alignment
        )

    # 11. Telemetry (tpu/telemetry.py): counters the tick already
    # computed for its own bookkeeping (the grid-vote plane's [G, W]
    # vote counts stand in for the [R, C, G, W] vote mask it fused).
    drops = jnp.sum(send & ~p2a_del) + jnp.sum(votes_dropped)
    tel = record(
        state.telemetry,
        proposals=admitted,
        phase1_msgs=probes_sent,
        phase2_msgs=(
            R * jnp.sum(is_new)
            + (R * C) * jnp.sum(timed_out)
            + jnp.sum(votes_cast)
        ),
        commits=committed - state.committed,
        executes=BS * jnp.sum(n_retire),
        drops=drops,
        retries=jnp.sum(timed_out),
        rotations=(
            (lc_shift > 0).astype(jnp.int32)
            if lc_shift is not None
            else 0
        ),
        resizes=n_resized,
        queue_depth=jnp.sum(next_slot - head) + jnp.sum(pending),
        queue_capacity=G * W,
        lat_hist_delta=lat_hist - state.lat_hist,
    )

    # 11.5 Span sampler (telemetry.record_spans): per-slot lifecycle
    # tick-stamps through the proxy-leader/grid/replica planes,
    # recorded from the masks this tick already computed (is_new /
    # grid votes / newly_chosen / retire). A traced-epoch switch marks
    # phase1 on every live span, so reconfiguration pauses are visible
    # in the Perfetto trace. Structurally OFF at spans=0 (the serve
    # loop sizes the reservoir).
    if telemetry_mod.span_slots(tel):
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=is_new,
            # Per-group batch-slot number at each ring position (OLD
            # head + ordinal); under rotation the pre-roll base makes
            # the numbering absolute, stable across rolls.
            slot_ids=(
                lc_base + state.head[:, None] + ord_of_pos
                if lc.compaction
                else state.head[:, None] + ord_of_pos
            ),
            # Cells sequenced THIS tick: OLD next_slot + ordinal (a
            # cell can retire and be re-sequenced in one tick).
            new_slot_ids=(
                lc_base
                + state.next_slot[:, None]
                + jnp.mod(w_iota[None, :] - state.next_slot[:, None], W)
                if lc.compaction
                else state.next_slot[:, None]
                + jnp.mod(w_iota[None, :] - state.next_slot[:, None], W)
            ),
            phase1_mark=(
                jnp.broadcast_to(lc_switch, (G,))
                if lc.reconfig
                else jnp.zeros((G,), bool)
            ),
            # A grid vote is visible once any cell's Phase2b arrived.
            voted=jnp.any(p2b_arrival <= 0, axis=(0, 1)),
            newly_chosen=newly_chosen,
            retire_mask=retire,
        )

    # 12. Window rotation rebase (tpu/lifecycle.py): when this tick's
    # shift fired, every absolute batch-slot number rebases in place —
    # ring positions (mod W), proxy ownership (mod P), and unbatcher
    # fan-out (mod U) are invariant under the aligned shift, and the
    # offset clocks are already relative. Absent at trace time under
    # LifecyclePlan.none().
    if lc.compaction:

        def _rebase(args):
            hd, ns, re_, rb, lgw = args
            return (
                lifecycle_mod.shift_counts(hd, lc_shift),
                lifecycle_mod.shift_counts(ns, lc_shift),
                lifecycle_mod.shift_counts(re_, lc_shift),
                # floor=0: a probe deferred across the roll (partition)
                # can hold a bound below the rotation threshold —
                # already satisfied by every watermark, so the clamp
                # is behavior-preserving.
                lifecycle_mod.shift_ids(rb, lc_shift, floor=0),
                lifecycle_mod.shift_ids(lgw, lc_shift),
            )

        # lax.cond: rebase sweeps only on the tick the roll fires.
        head, next_slot, rep_exec, rd_bound, lc_gcw = jax.lax.cond(
            lc_shift > 0,
            _rebase,
            lambda args: args,
            (
                head, next_slot, rep_exec, rd_bound,
                lcs.gc_watermark if lc.reconfig
                else jnp.zeros((0,), jnp.int32),
            ),
        )
        if lc.reconfig:
            lcs = dataclasses.replace(lcs, gc_watermark=lc_gcw)

    return BatchedCompartmentalizedState(
        bat_fill=fill,
        bat_arrival=bat_arrival,
        bat_shed=bat_shed,
        pending=pending,
        next_slot=next_slot,
        head=head,
        status=_pack_status(cfg, status),
        propose_tick=propose_tick,
        last_send=last_send,
        proxy_alive=proxy_alive,
        proxy_msgs=proxy_msgs,
        p2a_arrival=p2a_arrival,
        p2b_arrival=p2b_arrival,
        rep_arrival=rep_arrival,
        rep_exec=rep_exec,
        reply_arrival=reply_arrival,
        unbat_msgs=unbat_msgs,
        rd_issue=rd_issue,
        rd_bound=rd_bound,
        rd_count=rd_count,
        rd_probe=rd_probe,
        rd_row=rd_row,
        committed=committed,
        batches_committed=batches_committed,
        retired=retired,
        writes_done=writes_done,
        lat_sum=lat_sum,
        lat_hist=lat_hist,
        reads_done=reads_done,
        reads_shed=reads_shed,
        read_lat_sum=read_lat_sum,
        read_lat_hist=read_lat_hist,
        workload=wls,
        lifecycle=lcs,
        elastic=els,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedCompartmentalizedConfig,
    state: BatchedCompartmentalizedState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedCompartmentalizedState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedCompartmentalizedConfig,
    state: BatchedCompartmentalizedState,
    t,
) -> dict:
    """Device-side safety checks; returns traced boolean scalars so the
    checks also run under jit/vmap (the simtest harness vmaps them)."""
    W = cfg.window
    w_iota = jnp.arange(W, dtype=jnp.int32)
    ord_of_pos = (w_iota[None, :] - state.head[:, None]) % W
    live = ord_of_pos < (state.next_slot - state.head)[:, None]
    # Packed storage: invariants read the unpacked (int8) view.
    chosen = (_unpack_status(cfg, state.status, W) == CHOSEN) & live
    # Every chosen slot holds a full column-transversal quorum (every
    # row voted); votes saturate "arrived" until retirement clears them.
    votes_in = state.p2b_arrival <= 0
    quorum = jnp.all(jnp.any(votes_in, axis=1), axis=0)
    checks = {
        "quorum_ok": jnp.all(jnp.where(chosen, quorum, True)),
        "window_ok": jnp.all(
            (state.head <= state.next_slot)
            & (state.next_slot - state.head <= W)
        ),
        # Each replica's watermark sits between the retired prefix and
        # the allocated frontier.
        "watermark_ok": jnp.all(
            (state.rep_exec >= state.head[None, :])
            & (state.rep_exec <= state.next_slot[None, :])
        ),
        # Conservation: retired <= chosen batches; client completions
        # never exceed committed entries.
        "conserved": (
            (state.retired <= state.batches_committed)
            & (state.writes_done <= state.committed)
        ),
        "batcher_ok": jnp.all(
            (state.bat_fill >= 0) & (state.bat_fill <= 2 * cfg.batch_size)
        )
        & jnp.all(state.pending >= 0),
        # Lifecycle books: session ids conserved against completion
        # counts (and against the workload engine's totals when both
        # are active), rotation counters monotone, reconfiguration GC
        # armed (tpu/lifecycle.py).
        "lifecycle_ok": lifecycle_mod.invariants_ok(
            cfg.lifecycle,
            state.lifecycle,
            workload_completed=(
                state.workload.completed
                if cfg.lifecycle.has_sessions and cfg.workload.active
                else None
            ),
        ),
        # Elastic books: active/target counts inside [floor, capacity],
        # resize generation and event counters monotone.
        "elastic_ok": elastic_mod.invariants_ok(
            cfg.elastic, state.elastic
        ),
    }
    if cfg.read_window:
        occupied = state.rd_issue < INF
        # A bound is a commit-prefix watermark taken at issue; it can
        # never exceed the group's allocated frontier.
        checks["read_bound_ok"] = jnp.all(
            jnp.where(
                occupied,
                (state.rd_bound >= 0)
                & (state.rd_bound <= state.next_slot[None, :, None]),
                True,
            )
        )
    return checks


def stats(cfg, state, t) -> dict:
    """Host-side summary (one coalesced transfer via device_get of the
    fields it touches; never called inside the compiled loop)."""
    committed = int(state.committed)
    done = int(state.writes_done)
    hist = jax.device_get(state.lat_hist)
    cum = hist.cumsum()
    weight = int(hist.sum())
    p50 = int((cum >= max(1, (weight + 1) // 2)).argmax()) if weight else -1
    pm = jax.device_get(state.proxy_msgs)
    um = jax.device_get(state.unbat_msgs)
    reads = int(state.reads_done)
    return {
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "ticks": int(t),
        "committed_entries": committed,
        "batches_committed": int(state.batches_committed),
        "writes_done": done,
        "commit_latency_p50_ticks": p50,
        "latency_mean_ticks": (
            round(float(state.lat_sum) / done, 2) if done else -1.0
        ),
        "entries_per_batch": cfg.batch_size,
        "batcher_shed": int(state.bat_shed),
        "proxy_msgs_total": int(pm.sum()),
        # Load-balance factor over proxies: 1.0 = perfectly even.
        "proxy_imbalance": (
            round(float(pm.max()) / max(float(pm.mean()), 1e-9), 3)
            if pm.size
            else -1.0
        ),
        "unbatcher_replies_total": int(um.sum()),
        "reads_done": reads,
        "reads_shed": int(state.reads_shed),
        "read_latency_mean_ticks": (
            round(float(state.read_lat_sum) / reads, 2) if reads else -1.0
        ),
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
    lifecycle: LifecyclePlan = LifecyclePlan.none(),
    elastic: ElasticPlan = ElasticPlan.none(),
) -> BatchedCompartmentalizedConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every role plane — batchers, proxies, the 2x2 acceptor
    grid, replicas, unbatchers, and the read path — small enough to
    trace and compile in well under a second."""
    if elastic.active and not workload.active:
        # Elastic roles resize the admission split: an elastic
        # analysis config needs an active workload plan.
        workload = WorkloadPlan(arrival="constant", rate=2.0)
    return BatchedCompartmentalizedConfig(
        num_groups=4, grid_rows=2, grid_cols=2, num_proxy_leaders=4,
        num_batchers=2, num_unbatchers=2, num_replicas=3, window=16,
        batch_size=2, arrivals_per_tick=1, retry_timeout=8,
        read_rate=2, read_window=6, faults=faults, workload=workload,
        lifecycle=lifecycle, elastic=elastic,
    )
